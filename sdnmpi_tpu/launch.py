"""Controller launcher — the CLI face of the framework.

Equivalent of the reference's launch scripts
(reference: run_router.sh / run_router_debug.sh / run_router_no_monitor.sh,
which select Ryu apps and logging configs): three profiles map 1:1 —

    normal      INFO logging, monitor on          (run_router.sh)
    debug       DEBUG logging, monitor on         (run_router_debug.sh)
    no-monitor  INFO logging, monitor off         (run_router_no_monitor.sh)

The monitor's TSV stream goes to ``log/monitor.log`` like the reference's
logging.ini routes the Monitor logger (logging.ini:16-29); everything
else goes to stderr.

Since the southbound is the simulated fabric, the launcher also owns
topology construction (``--topo linear:4``, ``fattree:8``,
``dragonfly:8,32``, ``torus:4,4``) and an optional ``--demo`` traffic
generator that registers MPI ranks and fires a collective through the
fabric so a connected visualizer has something to watch.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import pathlib

from sdnmpi_tpu.config import Config
from sdnmpi_tpu.control.controller import Controller
from sdnmpi_tpu.topogen import (
    dragonfly,
    fattree,
    host_mac,
    linear,
    ring,
    torus,
    torus2d,
)

log = logging.getLogger("launch")


def parse_topo(spec: str):
    kind, _, args = spec.partition(":")
    nums = [int(x) for x in args.split(",") if x] if args else []
    if kind == "linear":
        return linear(*(nums or [4]))
    if kind == "ring":
        return ring(*(nums or [4]))
    if kind == "fattree":
        return fattree(*(nums or [4]))
    if kind == "dragonfly":
        return dragonfly(*(nums or [4, 4]))
    if kind == "torus":
        nums = nums or [4, 4]
        # 2 dims keep the historical torus2d naming; 3+ dims go N-d
        return torus2d(*nums) if len(nums) == 2 else torus(tuple(nums))
    raise SystemExit(f"unknown topology {spec!r}")


def setup_logging(profile: str, log_dir: str = "log") -> None:
    level = logging.DEBUG if profile == "debug" else logging.INFO
    logging.basicConfig(
        level=level, format="%(asctime)s %(name)s %(levelname)s %(message)s"
    )
    # split the Monitor TSV stream into its own file, like logging.ini
    pathlib.Path(log_dir).mkdir(exist_ok=True)
    monitor_logger = logging.getLogger("Monitor")
    handler = logging.FileHandler(pathlib.Path(log_dir) / "monitor.log")
    handler.setFormatter(logging.Formatter("%(message)s"))
    monitor_logger.addHandler(handler)
    monitor_logger.propagate = False


def run_demo(controller: Controller, fabric, n_ranks: int) -> None:
    """Register ranks and fire an alltoall so there is state to mirror."""
    from sdnmpi_tpu.protocol import openflow as of
    from sdnmpi_tpu.protocol.announcement import Announcement, AnnouncementType
    from sdnmpi_tpu.protocol.vmac import CollectiveType, VirtualMac

    n = min(n_ranks, len(fabric.hosts))
    if n < 2:
        log.warning("demo needs at least 2 ranks (have %d); skipping", n)
        return
    for rank in range(n):
        mac = host_mac(rank)
        fabric.hosts[mac].send(
            of.Packet(
                eth_src=mac,
                eth_dst="ff:ff:ff:ff:ff:ff",
                eth_type=of.ETH_TYPE_IP,
                ip_proto=of.IPPROTO_UDP,
                udp_dst=controller.config.announcement_port,
                payload=Announcement(AnnouncementType.LAUNCH, rank).encode(),
            )
        )
    vmac = VirtualMac(CollectiveType.ALLTOALL, 0, 1 % n).encode()
    fabric.hosts[host_mac(0)].send(
        of.Packet(eth_src=host_mac(0), eth_dst=vmac, eth_type=of.ETH_TYPE_IP)
    )
    flows = sum(len(t) for t in controller.router.fdb.fdb.values())
    log.info("demo: %d ranks, alltoall kicked off, %d flows installed", n, flows)


def run_serving_load(controller, fabric, args) -> dict:
    """``--tenants`` mode: drive the booted controller with the
    open-loop multi-tenant harness (control/loadgen.py) and log one
    report line per tenant — the CLI face of bench config 14. Hosts
    are split round-robin into tenants; every tenant offers
    ``--offered-rate`` unicast lookups/s for the run."""
    from sdnmpi_tpu.control.loadgen import LoadGen, TenantSpec

    macs = sorted(fabric.hosts)
    n = max(1, min(args.tenants, len(macs) // 2))
    groups = [macs[i::n] for i in range(n)]
    duration = args.duration if args.duration > 0 else 5.0
    tenants = []
    for i, group in enumerate(groups):
        if len(group) < 2:
            continue
        name = f"tenant{i}"
        for mac in group:
            controller.router.admission.assign(mac, name)
        tenants.append(TenantSpec(
            name=name, rate=args.offered_rate,
            n_requests=max(1, int(args.offered_rate * duration)),
            macs=tuple(group),
        ))
    if not tenants:
        log.warning("--tenants: not enough hosts for a tenant; skipping")
        return {}
    reports = LoadGen(controller, fabric).run(tenants)
    for r in reports.values():
        log.info(
            "serving load %s: %.0f routes/s (offered %d, completed %d, "
            "rejected %d) p50 %.2f ms p99 %.2f ms p999 %.2f ms",
            r.tenant, r.routes_per_s, r.offered, r.completed,
            r.rejected, r.p50_ms, r.p99_ms, r.p999_ms,
        )
    return reports


def config_from_args(args) -> Config:
    listen = getattr(args, "listen", None)
    if listen and not args.observe_links:
        # LLDP discovery is the ONLY link/host source in real-switch
        # mode (the simulated fabric's direct announcements don't exist)
        log.info("--listen implies --observe-links; enabling discovery")
    replica_index, replica_count = parse_ownership(
        getattr(args, "ownership", None)
    )
    return Config(
        oracle_backend=args.backend,
        enable_monitor=args.profile != "no-monitor",
        rpc_host=args.rpc_host,
        rpc_port=args.rpc_port,
        collective_policy=args.policy,
        trace_log=args.trace_log or "",
        profile_dir=args.profile_dir or "",
        observe_links=args.observe_links or bool(listen),
        lldp_reprobe_interval=args.lldp_reprobe,
        flow_idle_timeout=args.flow_idle_timeout,
        flow_hard_timeout=args.flow_hard_timeout,
        mesh_devices=args.mesh_devices,
        shard_oracle=getattr(args, "shard_oracle", False),
        ring_exchange=getattr(args, "ring_exchange", False),
        hier_oracle=getattr(args, "hier_oracle", False),
        hier_pod_target=getattr(args, "hier_pod_target", 0),
        hier_warm=getattr(args, "hier_warm", True),
        hier_snapshot=getattr(args, "hier_snapshot", True),
        event_log=args.event_log or "",
        event_log_max_bytes=getattr(args, "event_log_max_bytes", 0),
        recovery_plane=not getattr(args, "no_recovery", False),
        fabric_audit=not getattr(args, "no_fabric_audit", False),
        audit_switches_per_flush=getattr(
            args, "audit_switches_per_flush", 64
        ),
        traffic_plane=not getattr(args, "no_traffic_plane", False),
        sentinel_sample_per_flush=getattr(
            args, "sentinel_sample_per_flush", 64
        ),
        sentinel_divergence_factor=getattr(
            args, "sentinel_divergence_factor", 2.0
        ),
        sentinel_heal=getattr(args, "sentinel_heal", False),
        reconcile_max_per_flush=getattr(
            args, "reconcile_max_per_flush", 0
        ),
        schedule_collectives=getattr(args, "schedule_phases", None)
        is not None,
        schedule_phases=getattr(args, "schedule_phases", None) or 0,
        delta_reval=not getattr(args, "no_delta_reval", False),
        install_barriers=not getattr(args, "no_install_barriers", False),
        install_retry_max=getattr(args, "install_retry_max", 4),
        install_retry_backoff_s=getattr(args, "install_retry_backoff", 0.25),
        echo_interval_s=getattr(args, "echo_interval", 15.0),
        echo_timeout_s=getattr(args, "echo_timeout", 45.0),
        trace_dump=getattr(args, "trace_dump", None) or "",
        flight_recorder=not getattr(args, "no_flight_recorder", False),
        flight_dump_dir=getattr(args, "flight_dump", None) or "",
        flight_latency_threshold_s=getattr(
            args, "anomaly_latency_threshold", 0.0
        ),
        flight_p99_factor=getattr(args, "anomaly_p99_factor", 0.0),
        route_cache=getattr(args, "route_cache", True),
        admission_rate=getattr(args, "admission_rate", 0.0),
        compile_cache_dir=getattr(args, "compile_cache_dir", None) or "",
        warm_serving=getattr(args, "warm_serving", False),
        # the serving-load mode measures the coalesced window pipeline
        coalesce_routes=getattr(args, "tenants", 0) > 0,
        slo_targets=_slo_targets(getattr(args, "slo_target", None)),
        profile_dump_dir=getattr(args, "profile_dump", None) or "",
        replica_peer=getattr(args, "replica_peer", None) or "",
        replica_index=replica_index,
        replica_count=replica_count,
        replica_lease_interval_s=getattr(args, "lease_interval", 1.0),
        replica_lease_timeout_s=getattr(args, "lease_timeout", 3.0),
    )


def _slo_targets(specs) -> dict:
    """``--slo-target tenant:p99_ms[:avail]`` specs -> the
    Config.slo_targets dict; malformed specs fail the launch."""
    if not specs:
        return {}
    from sdnmpi_tpu.control.slo import parse_slo_target

    out = {}
    for spec in specs:
        try:
            t = parse_slo_target(spec)
        except ValueError as e:
            raise SystemExit(str(e))
        out[t.tenant] = (t.p99_ms, t.availability)
    return out


def parse_ownership(spec) -> tuple[int, int]:
    """``--ownership I/N`` -> (replica_index, replica_count); raises
    SystemExit on malformed input so a typo fails the launch instead of
    two replicas silently claiming the same shards. None (flag absent)
    -> (-1, 2): the index derives from the mesh's process order
    (ownership.mesh_replica_index)."""
    if not spec:
        return -1, 2
    try:
        idx_s, cnt_s = str(spec).split("/", 1)
        idx, cnt = int(idx_s), int(cnt_s)
    except ValueError:
        raise SystemExit(f"--ownership wants I/N, e.g. 0/2 (got {spec!r})")
    if cnt < 1 or not 0 <= idx < cnt:
        raise SystemExit(
            f"--ownership wants 0 <= I < N with N >= 1 (got {spec!r})"
        )
    return idx, cnt


def parse_distributed(spec: str) -> tuple[str, int, int]:
    """'HOST:PORT,NPROC,RANK' -> (coordinator, n_processes, process_id)
    for shardplane.mesh.init_multihost; raises SystemExit on malformed
    input so a typo fails the launch instead of silently running
    single-host."""
    try:
        coordinator, nproc_s, rank_s = spec.rsplit(",", 2)
        nproc, rank = int(nproc_s), int(rank_s)
    except ValueError:
        raise SystemExit(
            f"--distributed wants HOST:PORT,NPROC,RANK (got {spec!r})"
        )
    if ":" not in coordinator or nproc < 1 or not 0 <= rank < nproc:
        raise SystemExit(
            f"--distributed wants HOST:PORT,NPROC,RANK with "
            f"0 <= RANK < NPROC (got {spec!r})"
        )
    return coordinator, nproc, rank


async def run_replica_relay(controller, link, config) -> None:
    """Outbound half of the pair's replication stream (ISSUE 20): dial
    the peer's RPC WebSocket, relay the link's sends as
    ``replica_relay`` notifications, and drive the replica tick at the
    lease cadence — the async twin of the echo keepalive loop.
    Reconnects forever; sends while disconnected drop, and the
    sequence-gap protocol snapshot-backfills once the peer is back."""
    import json

    outbox: asyncio.Queue = asyncio.Queue(maxsize=4096)

    def enqueue(msg: dict) -> None:
        # QueueFull propagates into RpcReplicaLink.send's drop counter:
        # a wedged peer link opens a gap instead of growing unbounded
        outbox.put_nowait(json.dumps({
            "jsonrpc": "2.0", "method": "replica_relay", "params": [msg],
        }))

    link.bind_sender(enqueue)

    async def pump() -> None:
        import websockets

        while True:
            try:
                async with websockets.connect(
                    config.replica_peer
                ) as ws:
                    log.info("replica peer link up: %s", config.replica_peer)
                    while True:
                        await ws.send(await outbox.get())
            except asyncio.CancelledError:
                raise
            except Exception:
                await asyncio.sleep(
                    max(0.5, config.replica_lease_interval_s)
                )

    pump_task = asyncio.create_task(pump())
    try:
        while True:
            controller.replica.tick()
            await asyncio.sleep(max(0.1, config.replica_lease_interval_s))
    finally:
        pump_task.cancel()


async def amain(args) -> None:
    listen = getattr(args, "listen", None)
    if getattr(args, "distributed", None):
        # multi-host mesh: the distributed runtime must exist before
        # any mesh (or jax computation) is built
        from sdnmpi_tpu.shardplane.mesh import init_multihost

        init_multihost(*parse_distributed(args.distributed))
    config = config_from_args(args)
    if config.compile_cache_dir:
        # persistent compile cache (ISSUE 11): armed before ANY jax
        # computation so every serving kernel lands in / loads from it
        from sdnmpi_tpu.oracle.engine import enable_compile_cache

        if enable_compile_cache(config.compile_cache_dir):
            log.info(
                "persistent compile cache at %s", config.compile_cache_dir
            )
    if config.trace_log:
        from sdnmpi_tpu.utils.tracing import set_trace_sink

        set_trace_sink(config.trace_log)
    trace_collector = None
    if config.trace_dump:
        # in-memory span collector tee'd beside any file sink; rendered
        # as a Perfetto/chrome://tracing timeline on shutdown
        from sdnmpi_tpu.api.traceview import TraceCollector
        from sdnmpi_tpu.utils.tracing import add_trace_sink

        trace_collector = TraceCollector()
        add_trace_sink(trace_collector)
    if listen:
        # real-switch mode: the southbound is an OpenFlow 1.0 TCP server
        # (control/southbound.py) and the topology is whatever dials in —
        # the posture the reference got from `ryu-manager` (run_router.sh)
        if args.demo:
            raise SystemExit("--demo needs the simulated fabric (no --listen)")
        from sdnmpi_tpu.control.southbound import OFSouthbound

        host, _, port = listen.rpartition(":")
        fabric = OFSouthbound(host or "0.0.0.0", int(port))
        spec = None
    else:
        spec = parse_topo(args.topo)
        fabric = spec.to_fabric(
            wire=args.wire,
            discovery="packet" if args.observe_links else "direct",
        )
    ownership = None
    replica_link = None
    if config.replica_peer:
        # active/active pair (ISSUE 20): deterministic switch partition
        # by the mesh's process order, replication + lease heartbeats
        # relayed over the peer's RPC WebSocket
        from sdnmpi_tpu.control.ownership import (
            OwnershipMap,
            mesh_replica_index,
        )
        from sdnmpi_tpu.control.replica import FencedSouthbound, RpcReplicaLink

        index = (
            config.replica_index if config.replica_index >= 0
            else mesh_replica_index(config.replica_count)
        )
        ownership = OwnershipMap(config.replica_count, index)
        replica_link = RpcReplicaLink()
        fabric = FencedSouthbound(fabric, ownership, shared=False)
        log.info(
            "replica %d/%d: serving shards %s, peer %s",
            index, config.replica_count, ownership.shards_of(index),
            config.replica_peer,
        )
    controller = Controller(
        fabric, config, ownership=ownership, replica_link=replica_link
    )
    controller.attach()

    if args.restore:
        from sdnmpi_tpu.api.snapshot import load_checkpoint

        load_checkpoint(controller, args.restore)
        log.info("restored checkpoint from %s", args.restore)
    if spec is not None:
        log.info(
            "topology %s: %d switches, %d hosts",
            spec.name,
            spec.n_switches,
            spec.n_hosts,
        )
    if config.warm_serving and config.oracle_backend == "jax":
        # zero cold start (ISSUE 11): compile the serving path against
        # the booted topology before the first packet-in arrives
        warm = controller.topology_manager.topologydb.warm_serving(
            shapes=(8, config.coalesce_max_batch)
        )
        log.info(
            "serving path warmed in %.2f s (window buckets %s, hop "
            "budget %d)", warm["warm_s"], warm["shapes"], warm["max_len"],
        )

    tasks = []
    if controller.monitor is not None:
        tasks.append(asyncio.create_task(controller.monitor.run()))

    if spec is None:
        await fabric.serve()  # accept real OF 1.0 switches
        if config.echo_interval_s > 0 and hasattr(fabric, "run_echo"):
            # controller-side keepalive: kill half-open datapaths so
            # EventDatapathDown — and the reconcile on redial — fires
            tasks.append(asyncio.create_task(fabric.run_echo()))
        if (
            controller.discovery is not None
            and config.lldp_reprobe_interval > 0
        ):
            async def reprobe() -> None:
                # heal lost probe frames: discovery is event-driven, so
                # a dropped LLDP packet would otherwise hide a link
                # until the next port event
                while True:
                    await asyncio.sleep(config.lldp_reprobe_interval)
                    controller.discovery.probe()

            tasks.append(asyncio.create_task(reprobe()))
    else:
        chaos = None
        if getattr(args, "chaos", None) is not None:
            # live chaos demo: the same fault plan the recovery tests
            # soak under, stepping once per fabric clock tick
            from sdnmpi_tpu.control.faults import FaultPlan

            chaos = FaultPlan(
                seed=args.chaos,
                p_send_drop=0.05, p_send_stall=0.03, p_send_truncate=0.02,
                p_ack_drop=0.03, p_stats_delay=0.1,
                p_crash=0.05, p_redial=0.5, p_flap=0.08, p_restore=0.5,
                # silent table corruption (ISSUE 15): watch the audit
                # plane's divergence counters catch and heal it live
                p_mutate=0.03,
                mutate_priority=config.priority_default,
            ).attach(fabric)
            log.info("chaos fault plan armed (seed %d)", args.chaos)

        async def clock() -> None:
            # drive the fabric's flow-expiry clock (a real switch ages
            # its own flows; the sim needs the tick) — cheap no-op while
            # all installed flows are permanent (the default timeouts)
            loop = asyncio.get_running_loop()
            while True:
                fabric.tick(loop.time())
                if chaos is not None:
                    chaos.step()
                await asyncio.sleep(1.0)

        tasks.append(asyncio.create_task(clock()))
    if not args.no_rpc:
        from sdnmpi_tpu.api.rpc import RPCInterface

        rpc = RPCInterface(controller.bus, config)
        if replica_link is not None:
            # inbound half of the replication stream: the peer's
            # replica_relay notifications land in the link's inbox
            rpc.on_replica_relay = replica_link.ingest
        tasks.append(asyncio.create_task(rpc.serve()))
    elif replica_link is not None:
        log.warning("--replica-peer with --no-rpc: no inbound relay "
                    "endpoint; this replica can send but never receive")
    if replica_link is not None:
        tasks.append(asyncio.create_task(
            run_replica_relay(controller, replica_link, config)
        ))

    from sdnmpi_tpu.utils.tracing import STATS, device_trace

    try:
        with device_trace(config.profile_dir):
            if args.demo:
                run_demo(controller, fabric, args.demo_ranks)
            if getattr(args, "tenants", 0) > 0:
                if spec is None:
                    raise SystemExit(
                        "--tenants needs the simulated fabric (no --listen)"
                    )
                # bounded serving-load run: report and exit
                run_serving_load(controller, fabric, args)
            elif args.duration > 0:
                await asyncio.sleep(args.duration)
            else:
                await asyncio.Future()
    except asyncio.CancelledError:
        pass
    finally:
        summary = STATS.summary()
        if summary:
            log.info("oracle timing summary: %s", summary)
        metrics_dump = getattr(args, "metrics_dump", None)
        if metrics_dump:
            # Prometheus-style text exposition of the telemetry
            # registry ("-" = stdout) — same snapshot the RPC mirror's
            # update_telemetry feed broadcast live
            from sdnmpi_tpu.api.telemetry import dump

            dump(metrics_dump, snapshot=controller.telemetry())
            if metrics_dump != "-":
                log.info("metrics exposition written to %s", metrics_dump)
        if trace_collector is not None:
            # counter tracks from the metrics timeline render beside
            # the span slices (ISSUE 14) — one trace, both stories
            trace = trace_collector.dump(
                config.trace_dump, timeline=controller.timeline
            )
            log.info(
                "Perfetto trace (%d events) written to %s",
                len(trace["traceEvents"]), config.trace_dump,
            )
        if controller.profile_capture is not None:
            controller.profile_capture.close()
        if controller.flight is not None:
            if controller.flight.bundles:
                log.info(
                    "flight recorder froze %d diagnostic bundle(s); "
                    "last trigger: %s",
                    len(controller.flight.bundles),
                    controller.flight.bundles[-1]["trigger"],
                )
            controller.flight.disarm()
        if args.checkpoint:
            from sdnmpi_tpu.api.snapshot import save_checkpoint

            save_checkpoint(controller, args.checkpoint)
            log.info("checkpoint written to %s", args.checkpoint)
        if controller.event_logger is not None:
            log.info(
                "event log: %d events -> %s",
                controller.event_logger.n_events, config.event_log,
            )
            controller.event_logger.close()
        if spec is None:
            await fabric.close()  # stop accepting real switches
        for task in tasks:
            task.cancel()


def _nonneg_int(s: str) -> int:
    v = int(s)
    if v < 0:
        raise argparse.ArgumentTypeError(
            f"must be >= 0, got {v} (0 = auto)"
        )
    return v


def _nonneg_float(s: str) -> float:
    v = float(s)
    if v < 0:
        raise argparse.ArgumentTypeError(f"must be >= 0, got {v} (0 = off)")
    return v


def _pos_float(s: str) -> float:
    v = float(s)
    if v <= 0:
        raise argparse.ArgumentTypeError(f"must be > 0, got {v}")
    return v


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="sdnmpi_tpu", description="TPU-native SDN-MPI controller"
    )
    parser.add_argument(
        "--profile",
        choices=["normal", "debug", "no-monitor"],
        default="normal",
        help="launch profile (mirrors the reference's run_router*.sh)",
    )
    parser.add_argument("--topo", default="linear:4", help="topology spec, e.g. fattree:8")
    parser.add_argument(
        "--listen", default=None, metavar="[HOST:]PORT",
        help="real-switch mode: serve OpenFlow 1.0 over TCP instead of "
             "simulating --topo (e.g. --listen 6633); switches dial in "
             "like they dialed the reference's ryu-manager",
    )
    parser.add_argument(
        "--lldp-reprobe", type=float, default=15.0,
        help="periodic LLDP reflood seconds in --listen mode (0 = off)",
    )
    parser.add_argument("--backend", choices=["jax", "py"], default="jax")
    parser.add_argument("--rpc-host", default="127.0.0.1")
    parser.add_argument("--rpc-port", type=int, default=8080)
    parser.add_argument("--no-rpc", action="store_true", help="disable the WebSocket mirror")
    parser.add_argument(
        "--policy",
        choices=["balanced", "adaptive", "shortest"],
        default="balanced",
        help="routing policy for proactive collective batches",
    )
    parser.add_argument(
        "--observe-links",
        action="store_true",
        help="learn links/hosts via LLDP probes + traffic instead of "
        "direct entity events (the reference's --observe-links, "
        "run_router.sh:2)",
    )
    parser.add_argument(
        "--wire",
        action="store_true",
        help="round-trip every southbound message through the byte-level "
        "OpenFlow 1.0 codec (protocol/ofwire.py)",
    )
    parser.add_argument(
        "--flow-idle-timeout", type=int, default=0,
        help="idle expiry for routing flows in seconds (0 = permanent, "
        "the reference's only mode)",
    )
    parser.add_argument(
        "--flow-hard-timeout", type=int, default=0,
        help="hard expiry for routing flows in seconds (0 = permanent)",
    )
    parser.add_argument(
        "--mesh-devices", type=int, default=0,
        help="shard the DAG balancer over the first N local devices "
        "(0 = single-device)",
    )
    parser.add_argument(
        "--shard-oracle", action="store_true",
        help="promote --mesh-devices to the FULL pod-scale sharded "
        "oracle backend (sdnmpi_tpu/shardplane): APSP distances + next "
        "hops row-shard over the mesh and every routing entry point "
        "partitions its flow batch across it, with packed per-host "
        "readback. Bit-identical routes; requires --mesh-devices N > 0",
    )
    parser.add_argument(
        "--ring-exchange", dest="ring_exchange", action="store_true",
        help="stream the sharded oracle's distance/next-hop exchange "
        "through the double-buffered bidirectional ring (Pallas "
        "make_async_remote_copy DMA on a real TPU mesh, the ppermute "
        "twin elsewhere) with block-pipelined consumers, instead of "
        "the blocking XLA all-gather. bf16/int16 wire, bit-identical "
        "routes; requires --shard-oracle",
    )
    parser.add_argument(
        "--no-ring-exchange", dest="ring_exchange", action="store_false",
        help="keep the sharded legs on the XLA all-gather exchange "
        "(the PR-9 default; byte-identical differential escape hatch)",
    )
    parser.set_defaults(ring_exchange=False)
    parser.add_argument(
        "--hier-oracle", action="store_true",
        help="route through the hierarchical two-level oracle "
        "(oracle/hier.py): dense per-pod blocks + a compressed border "
        "skeleton replace every dense [V, V] plane — O(pods x "
        "pod_size^2) memory, 65k-switch fabrics on one slice. Path "
        "lengths bit-identical to the dense oracle; with "
        "--mesh-devices the pod blocks shard one block-shard per "
        "device and --ring-exchange moves the border plane over the "
        "ring",
    )
    parser.add_argument(
        "--hier-pod-target", type=int, default=0,
        help="partitioner pod-size target for unannotated fabrics "
        "under --hier-oracle (0 = ~sqrt(V) auto)",
    )
    parser.add_argument(
        "--hier-warm", dest="hier_warm", action="store_true",
        help="precompile the full hierarchical program ladder "
        "(pod-stack APSP buckets, pow2 Jacobi pull-sweep shapes, fused "
        "composition, batch fdb) during warm_serving, so the first "
        "route after boot replays cached executables instead of "
        "tracing (default: on)",
    )
    parser.add_argument(
        "--no-hier-warm", dest="hier_warm", action="store_false",
        help="skip the hierarchical warm ladder — first route pays "
        "full trace/compile cost (the differential escape hatch; "
        "routes stay bit-identical)",
    )
    parser.set_defaults(hier_warm=True)
    parser.add_argument(
        "--hier-snapshot", dest="hier_snapshot", action="store_true",
        help="persist the hier oracle's lazy border-distance row plane "
        "through api/snapshot beside the route-cache memo — a "
        "restarted controller inherits the warm level-2 plane "
        "(topology-digest guarded; default: on)",
    )
    parser.add_argument(
        "--no-hier-snapshot", dest="hier_snapshot",
        action="store_false",
        help="exclude the border plane from checkpoints and ignore it "
        "on restore — restart pays the cold lazy rebuild (the "
        "differential escape hatch; routes stay bit-identical)",
    )
    parser.set_defaults(hier_snapshot=True)
    parser.add_argument(
        "--distributed", metavar="HOST:PORT,NPROC,RANK",
        help="join a multi-host shardplane mesh: initialize "
        "jax.distributed against the coordinator at HOST:PORT as "
        "process RANK of NPROC, so every controller host's chips form "
        "one global device set for --mesh-devices/--shard-oracle "
        "(shardplane.mesh.init_multihost; NPROC=1 is a no-op)",
    )
    parser.add_argument(
        "--no-recovery", action="store_true",
        help="disable the failure-domain recovery plane (desired-flow "
        "reconciliation, install retries, anti-entropy) — restores the "
        "fire-and-forget legacy for differential runs",
    )
    parser.add_argument(
        "--schedule-phases", type=_nonneg_int, default=None, metavar="K",
        help="enable the device-side collective phase scheduler "
        "(sdnmpi_tpu/sched): block-installed collectives decompose into "
        "K link-load-balanced phases installed with barrier-acked "
        "boundaries (K is pow2-rounded and clamped at 32; 0 = auto). "
        "Omit the flag for the bit-identical single-shot install path",
    )
    parser.add_argument(
        "--no-delta-reval", action="store_true",
        help="disable delta-narrowed flow revalidation: every topology "
        "change re-routes EVERY installed flow and collective (the "
        "differential escape hatch; narrowed and full passes leave "
        "bit-identical FDB + desired state)",
    )
    parser.add_argument(
        "--no-install-barriers", action="store_true",
        help="do not terminate batched install windows with "
        "OFPT_BARRIER_REQUEST (no acked installs)",
    )
    parser.add_argument(
        "--install-retry-max", type=int, default=4,
        help="bounded retries per switch for dropped/un-acked install "
        "windows before escalating to a full resync",
    )
    parser.add_argument(
        "--install-retry-backoff", type=float, default=0.25,
        help="base seconds of the install retry queue's exponential "
        "backoff (doubles per attempt, +25%% seeded jitter)",
    )
    parser.add_argument(
        "--echo-interval", type=float, default=15.0,
        help="controller-side echo keepalive period for real TCP "
        "datapaths in --listen mode, seconds (0 = off)",
    )
    parser.add_argument(
        "--echo-timeout", type=float, default=45.0,
        help="seconds without an echo reply before a half-open "
        "datapath is disconnected",
    )
    parser.add_argument(
        "--replica-peer", default=None,
        help="peer controller's RPC WebSocket URL (e.g. "
        "ws://host:8080/v1.0/sdnmpi/ws): run as one replica of an "
        "active/active pair — switch ownership is partitioned, stores "
        "replicate, and a dead peer's shards are adopted (unset = "
        "single controller, unchanged serving path)",
    )
    parser.add_argument(
        "--ownership", default=None,
        help="this replica's slot as I/N (e.g. 0/2); omit to derive "
        "the index from the mesh's process order",
    )
    parser.add_argument(
        "--lease-interval", type=_pos_float, default=1.0,
        help="replica lease heartbeat period, seconds",
    )
    parser.add_argument(
        "--lease-timeout", type=_pos_float, default=3.0,
        help="seconds of peer silence before its lease expires and "
        "its shards are adopted (epoch bump + reconcile-on-adopt)",
    )
    parser.add_argument(
        "--no-fabric-audit", action="store_true",
        help="disable the fabric ground-truth audit plane "
        "(control/audit.py): per-flush OFPST_FLOW sweeps diffing every "
        "switch's actual table against the desired store, healing "
        "confirmed divergence as targeted re-drives",
    )
    parser.add_argument(
        "--audit-switches-per-flush", type=_nonneg_int, default=64,
        metavar="N",
        help="switches audited per Monitor flush (the sweep's "
        "round-robin pacing; 0 = the whole fabric every flush)",
    )
    parser.add_argument(
        "--no-traffic-plane", action="store_true",
        help="disable the measured traffic matrix + shadow route-"
        "quality sentinel (oracle/trafficplane.py, control/sentinel.py):"
        " per-flush EWMA folding of the audit plane's attributed byte "
        "deltas into a device-resident per-tenant src->dst rate matrix,"
        " re-scored against a fresh oracle optimum",
    )
    parser.add_argument(
        "--sentinel-sample-per-flush", type=_nonneg_int, default=64,
        metavar="N",
        help="installed routes the sentinel re-scores per stats flush "
        "against a fresh oracle optimum for the measured matrix "
        "(round-robin pacing; 0 = the whole installed population)",
    )
    parser.add_argument(
        "--sentinel-divergence-factor", type=_pos_float, default=2.0,
        metavar="F",
        help="measured-vs-modeled hottest-link ratio at which the "
        "sentinel confirms the routes no longer fit the traffic "
        "(counts sentinel_divergence_total{tenant} and freezes a "
        "flight bundle naming the worst tenant/collective/pod-pair)",
    )
    parser.add_argument(
        "--sentinel-heal", action="store_true",
        help="let the sentinel re-drive the worst diverging pair "
        "through the install plane when divergence confirms (default "
        "OFF: the channel observes only and never mutates routing)",
    )
    parser.add_argument(
        "--reconcile-max-per-flush", type=_nonneg_int, default=0,
        metavar="N",
        help="cap datapath-up reconciles served per flush window so a "
        "power-cycled pod redialing at once cannot flood the install "
        "plane (deferred reconciles drain on later flushes, counted in "
        "reconcile_deferred_total; 0 = unshaped)",
    )
    parser.add_argument(
        "--chaos", type=int, default=None, metavar="SEED",
        help="arm a seeded fault-injection plan (control/faults.py) "
        "against the simulated fabric: switch crashes/redials, link "
        "flaps, dropped/stalled/truncated installs, delayed stats — "
        "one chaos step per fabric clock tick; watch the recovery "
        "counters converge it back",
    )
    parser.add_argument(
        "--tenants", type=_nonneg_int, default=0, metavar="N",
        help="serving-load mode (ISSUE 11): split the simulated "
        "fabric's hosts into N tenants and drive the live controller "
        "with the open-loop multi-tenant harness (control/loadgen.py) "
        "for --duration seconds (default 5), reporting per-tenant "
        "routes/s and p50/p99/p999; implies route coalescing. 0 = off",
    )
    parser.add_argument(
        "--offered-rate", type=_pos_float, default=200.0, metavar="R",
        help="offered load per tenant in requests/second for --tenants "
        "(open-loop: arrivals are scheduled from this rate alone, so "
        "queueing past capacity shows up as tail latency, not as "
        "silently throttled load)",
    )
    parser.add_argument(
        "--route-cache", dest="route_cache", action="store_true",
        help="memoize reaped route windows / collective results in "
        "front of the oracle, invalidated through the topology delta "
        "log (oracle/routecache.py; the default)",
    )
    parser.add_argument(
        "--no-route-cache", dest="route_cache", action="store_false",
        help="serve every request through the oracle dispatch path "
        "(the PR-10 behavior, byte-identical — the differential "
        "escape hatch)",
    )
    parser.set_defaults(route_cache=True)
    parser.add_argument(
        "--admission-rate", type=_nonneg_float, default=0.0,
        metavar="RATE",
        help="per-tenant admission rate in packet-ins/second "
        "(control/admission.py): requests past a tenant's token bucket "
        "drop at the door so one tenant's storm cannot starve the "
        "rest. 0 = admit everything (the default)",
    )
    parser.add_argument(
        "--compile-cache-dir", metavar="DIR",
        help="persistent JAX compilation cache: compiled serving "
        "kernels land on disk and a restarted controller reloads them "
        "instead of re-compiling (kills the 18-22 s cold start)",
    )
    parser.add_argument(
        "--warm-serving", action="store_true",
        help="compile the serving path (APSP refresh + window "
        "extraction buckets) against the booted topology at launch, "
        "before the first packet-in arrives",
    )
    parser.add_argument("--trace-log", help="JSONL structured trace log path")
    parser.add_argument(
        "--trace-dump", metavar="PATH",
        help="write the run's span trees as a Perfetto/chrome://tracing "
        "JSON timeline on shutdown (api/traceview.py)",
    )
    parser.add_argument(
        "--no-flight-recorder", action="store_true",
        help="disable the in-memory flight recorder (span-tree ring, "
        "anomaly triggers, histogram exemplars)",
    )
    parser.add_argument(
        "--flight-dump", metavar="DIR",
        help="write each anomaly trigger's diagnostic bundle as a JSON "
        "file under DIR (default: bundles stay in memory, readable via "
        "the flight_dump RPC method)",
    )
    parser.add_argument(
        "--anomaly-latency-threshold", type=float, default=0.0,
        metavar="SECONDS",
        help="freeze a diagnostic bundle when a route/install/re-route "
        "latency observation provably exceeds this bound (0 = off)",
    )
    parser.add_argument(
        "--anomaly-p99-factor", type=float, default=0.0, metavar="FACTOR",
        help="freeze a bundle when an interval's estimated p99 exceeds "
        "FACTOR x the rolling baseline (0 = off)",
    )
    parser.add_argument(
        "--slo-target", action="append", metavar="TENANT:P99_MS[:AVAIL]",
        help="per-tenant serving SLO (repeatable; ISSUE 14): the Router "
        "feeds the tenant's latency histogram and a multi-window "
        "burn-rate trigger freezes a diagnostic bundle naming the "
        "burning tenant and the dominant pipeline stage when the error "
        "budget burns (e.g. --slo-target victim:50:0.999)",
    )
    parser.add_argument(
        "--profile-dump", metavar="DIR",
        help="anomaly-armed device profiling: when a flight-recorder "
        "trigger fires, open a jax.profiler capture window under DIR "
        "for a few seconds — the profile OF the incident, zero "
        "steady-state overhead",
    )
    parser.add_argument(
        "--event-log",
        help="JSONL control-plane event log (every bus event, one line)",
    )
    parser.add_argument(
        "--event-log-max-bytes", type=int, default=0,
        help="rotate the event log to <path>.1 at this size (0 = never)",
    )
    parser.add_argument(
        "--metrics-dump", metavar="PATH",
        help="write the telemetry registry as a Prometheus-style text "
        "exposition on shutdown ('-' = stdout)",
    )
    parser.add_argument("--profile-dir", help="jax.profiler trace output dir")
    parser.add_argument("--demo", action="store_true", help="generate demo MPI traffic")
    parser.add_argument("--demo-ranks", type=int, default=8)
    parser.add_argument("--duration", type=float, default=0, help="run time in seconds (0 = forever)")
    parser.add_argument("--checkpoint", help="write a state checkpoint on shutdown")
    parser.add_argument("--restore", help="restore state from a checkpoint file")
    return parser


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)

    setup_logging(args.profile)
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
