"""Per-tenant admission control for the serving plane (ISSUE 11).

Multi-tenant fairness under non-uniform offered load is exactly the
regime where serving throughput collapses without admission control
(Throughput-Optimized Networks at Scale, arxiv 2605.27963): one
tenant's alltoall storm fills the route pipeline and every other
tenant's latency-sensitive request queues behind it. The Router gates
every packet-in through an :class:`AdmissionControl` of per-tenant
token buckets: a tenant is whatever the operator registered the source
MAC under (:meth:`AdmissionControl.assign`; unregistered MACs are their
own tenant), each tenant refills at ``Config.admission_rate`` requests
per second (a per-tenant override is possible) up to a burst depth of
``Config.admission_burst``, and a request arriving to an empty bucket
is dropped at the door — before any routing work — and counted in
``admission_rejections_total{tenant=...}``. ``admission_rate=0`` (the
default) admits everything: the pre-serving-plane behavior,
byte-identical.

Open-loop consequence (the config-14 harness measures it): with
admission off, offered load past capacity grows the coalescer queue
without bound and EVERY tenant's p99 diverges; with it on, the
aggressor is clipped at its admitted rate and the victim's p99 stays
within a small factor of its unloaded latency.
"""

from __future__ import annotations

import time
from typing import Optional

from sdnmpi_tpu.utils.metrics import REGISTRY

_m_rejections = REGISTRY.labeled_counter(
    "admission_rejections_total", "tenant",
    "packet-ins dropped at the admission gate, per tenant",
)
_m_admitted = REGISTRY.counter(
    "admission_admitted_total",
    "packet-ins past the admission gate while rate limiting was armed",
)


class TokenBucket:
    """Continuous-refill token bucket: ``rate`` tokens/s up to
    ``burst``. ``take`` is two float ops on the hot path."""

    __slots__ = ("rate", "burst", "tokens", "t")

    def __init__(self, rate: float, burst: float, now: float) -> None:
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst  # a fresh tenant may burst immediately
        self.t = now

    def take(self, now: float, n: float = 1.0) -> bool:
        self.tokens = min(self.burst, self.tokens + (now - self.t) * self.rate)
        self.t = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


class AdmissionControl:
    """Per-tenant packet-in rate limiting for the Router.

    ``rate == 0`` disables the gate entirely (every request admitted,
    zero bookkeeping — the escape hatch the PR-10 byte-identity pin
    rides on). Buckets are created lazily per tenant on first arrival.
    """

    def __init__(self, rate: float = 0.0, burst: float = 32.0) -> None:
        self.rate = float(rate)
        self.burst = float(burst)
        #: src MAC -> tenant name (unregistered MACs tenant as themselves)
        self._tenants: dict[str, str] = {}
        #: tenant -> rate override (None = Config.admission_rate)
        self._rates: dict[str, float] = {}
        self._buckets: dict[str, TokenBucket] = {}

    def assign(
        self, mac: str, tenant: str, rate: Optional[float] = None
    ) -> None:
        """Bind a source MAC to a tenant (idempotent); ``rate``
        optionally overrides the uniform per-tenant rate for it."""
        self._tenants[mac] = tenant
        if rate is not None:
            self._rates[tenant] = float(rate)
            self._buckets.pop(tenant, None)  # rebuild at the new rate

    def tenant_of(self, mac: str) -> str:
        return self._tenants.get(mac, mac)

    def admit(self, src_mac: str, now: Optional[float] = None) -> bool:
        """True iff the tenant behind ``src_mac`` has a token; a False
        increments the tenant's rejection counter. With no rate armed
        (globally and for this tenant) this is one dict miss + compare."""
        tenant = self._tenants.get(src_mac, src_mac)
        rate = self._rates.get(tenant, self.rate)
        if rate <= 0:
            return True
        now = time.monotonic() if now is None else now
        bucket = self._buckets.get(tenant)
        if bucket is None:
            bucket = self._buckets[tenant] = TokenBucket(
                rate, self.burst, now
            )
        if bucket.take(now):
            _m_admitted.inc()
            return True
        _m_rejections.inc(tenant)
        return False

    def rejections(self, tenant: str) -> int:
        """Current rejection count for one tenant (loadgen reads this
        synchronously around each injection to attribute drops)."""
        return _m_rejections.values.get(tenant, 0)
