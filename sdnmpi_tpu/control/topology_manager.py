"""Topology manager app.

Equivalent of the reference's ``TopologyManager``
(reference: sdnmpi/topology.py:59-202): owns the TopologyDB, ingests
discovery events, installs per-switch bootstrap flows (broadcast ->
controller at the broadcast priority; IPv6-multicast drop installed
reactively), answers route queries, and performs controlled network-wide
broadcasts out of edge ports only.

Upgrades over the reference:
- ``FindAllRoutesRequest`` works (the reference's was dead-broken,
  topology.py:48,147).
- ``FindRoutesBatchRequest`` resolves a whole collective's pairs in one
  oracle call.
- Per-link utilization (fed by the Monitor's EventPortStats) is kept here
  beside the topology, ready for congestion-aware scoring.
"""

from __future__ import annotations

import logging

from sdnmpi_tpu.config import Config, DEFAULT_CONFIG
from sdnmpi_tpu.control import events as ev
from sdnmpi_tpu.control.bus import EventBus
from sdnmpi_tpu.core.topology_db import TopologyDB
from sdnmpi_tpu.protocol import openflow as of
from sdnmpi_tpu.utils.mac import BROADCAST_MAC, is_ipv6_multicast
from sdnmpi_tpu.utils.metrics import REGISTRY

log = logging.getLogger("TopologyManager")

# device-side congestion analytics (ISSUE 7): one jitted top-k pass per
# EventStatsFlush over the published utilization plane, decoded to the
# report served by CongestionReportRequest; these gauges are the
# scrape-able headline figures
_m_hot_bps = REGISTRY.gauge(
    "congestion_hot_link_bps",
    "measured bps of the fabric's hottest directed link (device top-k "
    "pass per Monitor flush)",
)
_m_hot_collectives = REGISTRY.gauge(
    "congestion_hot_collectives",
    "installed collectives whose routed blocks ride a current top-k hot "
    "link",
)
_m_host_sampled = REGISTRY.gauge(
    "congestion_host_sampled",
    "1 when the congestion report is served from the Monitor's host "
    "samples (Config.hier_oracle skips the dense device UtilPlane), "
    "0 when the jitted device top-k pass serves it",
)


class TopologyManager:
    name = "TopologyManager"

    def __init__(
        self,
        bus: EventBus,
        southbound,
        config: Config = DEFAULT_CONFIG,
    ) -> None:
        self.bus = bus
        self.southbound = southbound
        self.config = config
        self.topologydb = TopologyDB(
            backend=config.oracle_backend,
            pad_multiple=config.switch_pad_multiple,
            max_diameter=config.max_diameter,
            mesh_devices=config.mesh_devices,
            shard_oracle=config.shard_oracle,
            ring_exchange=config.ring_exchange,
            delta_repair_threshold=config.delta_repair_threshold,
            route_cache=config.route_cache,
            route_cache_max_entries=config.route_cache_max_entries,
            hier_oracle=config.hier_oracle,
            hier_pod_target=config.hier_pod_target,
            hier_fused=config.hier_fused,
            hier_warm=config.hier_warm,
        )
        #: (src_dpid, src_port) -> latest utilization of that directed
        #: link in bps: max of the sender's tx stream and the receiver's
        #: rx stream (the reference logs both, sdnmpi/monitor.py:79-88;
        #: ingesting both means a one-sided counter stall cannot hide a
        #: hot link). Pruned when links/switches leave, so a dead link's
        #: last sample can never keep biasing the congestion base.
        self.link_util: dict[tuple[int, int], float] = {}
        self._tx_util: dict[tuple[int, int], float] = {}
        self._rx_util: dict[tuple[int, int], float] = {}
        #: device-resident twin of link_util (oracle/utilplane.py):
        #: samples stage here too and flush to a persistent on-device
        #: [V, V] tensor once per Monitor pass, so the oracle's base
        #: cost needs no per-call host rebuild. The host dict stays
        #: authoritative for snapshots/RPC and as the differential
        #: oracle; None on the pure-Python backend (which has no
        #: balancing to feed) or when Config.util_plane is off.
        self.util_plane = None
        if (
            config.oracle_backend == "jax" and config.util_plane
            and not config.hier_oracle
            # the device plane IS a dense [V, V] tensor — exactly the
            # ceiling the hierarchical oracle escapes; under hier the
            # host dict stays authoritative and the oracle steers
            # through its pod-aggregated view (oracle/hier.py)
        ):
            from sdnmpi_tpu.oracle.utilplane import UtilPlane

            self.util_plane = UtilPlane(
                config.util_ewma_alpha,
                stale_horizon_s=config.util_stale_horizon_s,
            )
        #: (dst_dpid, dst_port) -> (src_dpid, src_port) of the directed
        #: link arriving there, for attributing rx samples
        self._link_rev: dict[tuple[int, int], tuple[int, int]] = {}

        bus.subscribe(ev.EventDatapathUp, self._datapath_up)
        bus.subscribe(ev.EventSwitchEnter, lambda e: self.topologydb.add_switch(e.switch))
        bus.subscribe(ev.EventPortAdd, lambda e: self.topologydb.add_switch(e.switch))
        bus.subscribe(ev.EventSwitchLeave, self._switch_leave)
        bus.subscribe(ev.EventPortDelete, self._port_delete)
        bus.subscribe(ev.EventLinkAdd, self._link_add)
        bus.subscribe(ev.EventLinkDelete, self._link_delete)
        bus.subscribe(ev.EventHostAdd, lambda e: self.topologydb.add_host(e.host))
        bus.subscribe(ev.EventPacketIn, self._packet_in)
        bus.subscribe(ev.EventPortStats, self._port_stats)
        bus.subscribe(ev.EventStatsFlush, self._stats_flush)

        bus.provide(ev.CurrentTopologyRequest, self._current_topology)
        bus.provide(ev.FindRouteRequest, self._find_route)
        bus.provide(ev.FindAllRoutesRequest, self._find_all_routes)
        bus.provide(ev.FindRoutesBatchRequest, self._find_routes_batch)
        bus.provide(ev.DispatchRoutesBatchRequest, self._dispatch_routes_batch)
        bus.provide(ev.UtilEpochRequest, self._util_epoch)
        bus.provide(ev.FindCollectiveRoutesRequest, self._find_routes_collective)
        bus.provide(ev.BroadcastRequest, self._broadcast_request)
        bus.provide(ev.CongestionReportRequest, self._congestion_report)

        #: latest device-side congestion analytics (ISSUE 7): refreshed
        #: per EventStatsFlush once the utilization plane is bound;
        #: served over the bus / mirrored into the telemetry snapshot
        self.congestion: dict = {}
        #: fabric audit plane (ISSUE 15; wired by the Controller): its
        #: per-flow byte attribution becomes the congestion report's
        #: measured-vs-modeled block. None = no measured column.
        self.audit = None

    # -- bootstrap flows (reference: sdnmpi/topology.py:94-108) -----------

    def _datapath_up(self, event: ev.EventDatapathUp) -> None:
        mod = of.FlowMod(
            match=of.Match(dl_dst=BROADCAST_MAC),
            actions=(of.ActionOutput(of.OFPP_CONTROLLER),),
            priority=self.config.priority_broadcast,
        )
        self.southbound.flow_mod(event.dpid, mod)

    def _install_multicast_drop(self, dpid: int, dst: str) -> None:
        # reactive drop rule for IPv6 multicast (reference: topology.py:82-92)
        mod = of.FlowMod(
            match=of.Match(dl_dst=dst),
            actions=(),
            priority=self.config.priority_control,
        )
        self.southbound.flow_mod(dpid, mod)

    # -- packet-in (reference: sdnmpi/topology.py:110-131) ----------------

    def _packet_in(self, event: ev.EventPacketIn) -> None:
        dst = event.pkt.eth_dst
        if is_ipv6_multicast(dst):
            self._install_multicast_drop(event.dpid, dst)
            return
        if dst != BROADCAST_MAC:
            return
        # announcement packets belong to the ProcessManager
        if event.pkt.udp_dst == self.config.announcement_port:
            return
        self._do_broadcast(event.pkt, event.dpid, event.in_port)

    # -- request handlers -------------------------------------------------

    def _current_topology(self, req: ev.CurrentTopologyRequest) -> ev.CurrentTopologyReply:
        return ev.CurrentTopologyReply(self.topologydb)

    def _find_route(self, req: ev.FindRouteRequest) -> ev.FindRouteReply:
        return ev.FindRouteReply(self.topologydb.find_route(req.src_mac, req.dst_mac))

    def _find_all_routes(self, req: ev.FindAllRoutesRequest) -> ev.FindAllRoutesReply:
        fdbs, truncated = self.topologydb.find_all_routes(
            req.src_mac, req.dst_mac,
            max_paths=self.config.max_enumerated_paths,
        )
        return ev.FindAllRoutesReply(fdbs, truncated)

    def _find_routes_batch(
        self, req: ev.FindRoutesBatchRequest
    ) -> ev.FindRoutesBatchReply:
        if req.policy == "balanced":
            fdbs, max_congestion = self.topologydb.find_routes_batch_balanced(
                req.pairs,
                link_util=self.routing_util(),
                alpha=self.config.congestion_alpha,
                chunk=self.config.ecmp_chunk,
                link_capacity=self.config.link_capacity_bps,
                ecmp_ways=self.config.ecmp_ways,
                rounds=self.config.balance_rounds,
                dag_threshold=self.config.dag_flow_threshold,
            )
            return ev.FindRoutesBatchReply(fdbs, max_congestion)
        if req.policy == "adaptive":
            fdbs, n_detours, max_congestion = (
                self.topologydb.find_routes_batch_adaptive(
                    req.pairs,
                    link_util=self.routing_util(),
                    ugal_candidates=self.config.ugal_candidates,
                    ugal_bias=self.config.ugal_bias,
                    alpha=self.config.congestion_alpha,
                    link_capacity=self.config.link_capacity_bps,
                    ecmp_ways=self.config.ecmp_ways,
                )
            )
            if n_detours:
                log.info("UGAL detoured %d of %d pairs", n_detours, len(req.pairs))
            return ev.FindRoutesBatchReply(fdbs, max_congestion)
        if req.policy != "shortest":
            log.warning(
                "unknown routing policy %r: falling back to shortest-path",
                req.policy,
            )
        return ev.FindRoutesBatchReply(self.topologydb.find_routes_batch(req.pairs))

    def _dispatch_routes_batch(
        self, req: ev.DispatchRoutesBatchRequest
    ) -> ev.DispatchRoutesBatchReply:
        """Split-phase leg of _find_routes_batch: launch, don't decode.
        Policy knobs are resolved from config exactly like the blocking
        handler, so a dispatched window routes identically to the same
        pairs through FindRoutesBatchRequest."""
        cfg = self.config
        if req.dirty is not None and req.policy == "shortest":
            # delta-narrowed churn re-scoring: the dirty set rides to
            # the oracle as a mask tensor and the window's touched
            # array feeds the drain-attribution telemetry
            # (control/router.py router_reval_flows_drained_total)
            return ev.DispatchRoutesBatchReply(
                self.topologydb.find_routes_batch_delta_dispatch(
                    req.pairs, req.dirty
                )
            )
        kwargs = {}
        if req.policy == "balanced":
            kwargs = dict(
                link_util=self.routing_util(),
                alpha=cfg.congestion_alpha,
                chunk=cfg.ecmp_chunk,
                link_capacity=cfg.link_capacity_bps,
                ecmp_ways=cfg.ecmp_ways,
                rounds=cfg.balance_rounds,
                dag_threshold=cfg.dag_flow_threshold,
            )
        elif req.policy == "adaptive":
            kwargs = dict(
                link_util=self.routing_util(),
                ugal_candidates=cfg.ugal_candidates,
                ugal_bias=cfg.ugal_bias,
                alpha=cfg.congestion_alpha,
                link_capacity=cfg.link_capacity_bps,
                ecmp_ways=cfg.ecmp_ways,
            )
        return ev.DispatchRoutesBatchReply(
            self.topologydb.find_routes_batch_dispatch(
                req.pairs, policy=req.policy, **kwargs
            )
        )

    def _util_epoch(self, req: ev.UtilEpochRequest) -> ev.UtilEpochReply:
        return ev.UtilEpochReply(
            self.util_plane.epoch if self.util_plane is not None else 0
        )

    def _find_routes_collective(
        self, req: ev.FindCollectiveRoutesRequest
    ) -> ev.FindCollectiveRoutesReply:
        cfg = self.config
        kwargs = dict(
            link_util=self.routing_util(),
            alpha=cfg.congestion_alpha,
            link_capacity=cfg.link_capacity_bps,
            ecmp_ways=cfg.ecmp_ways,
            rounds=cfg.balance_rounds,
        )
        if req.policy == "adaptive":
            kwargs["ugal_candidates"] = cfg.ugal_candidates
            kwargs["ugal_bias"] = cfg.ugal_bias
        if req.schedule is not None:
            # phase-scheduler leg (ISSUE 8): the reply's routes is a
            # PhasedFlowProgram with every phase's device program
            # already dispatched — the Router reaps and installs phase
            # k while phases k+1..K compute
            return ev.FindCollectiveRoutesReply(
                self.topologydb.find_routes_collective_phased(
                    req.macs, req.src_idx, req.dst_idx,
                    policy=req.policy, n_phases=int(req.schedule),
                    **kwargs,
                )
            )
        routes = self.topologydb.find_routes_collective(
            req.macs, req.src_idx, req.dst_idx, policy=req.policy, **kwargs
        )
        return ev.FindCollectiveRoutesReply(routes)

    def _broadcast_request(self, req: ev.BroadcastRequest) -> ev.BroadcastReply:
        self._do_broadcast(req.pkt, req.src_dpid, req.src_in_port)
        return ev.BroadcastReply()

    # -- broadcast (reference: sdnmpi/topology.py:150-177) ----------------

    def _do_broadcast(self, pkt: of.Packet, src_dpid: int, src_in_port: int) -> None:
        """Flood to every edge port in the network — any switch port
        without an inter-switch link (and below the reserved range) —
        excluding the ingress port, exactly the reference's flood set
        (topology.py:157-177, ``_is_edge_port`` at :163-168). Flooding
        only *discovered-host* ports would strand a host that has never
        sent a packet: it could never receive the broadcast that
        bootstraps it."""
        for dpid in sorted(self.topologydb.switches):
            switch = self.topologydb.switches[dpid]
            inter = {
                link.src.port_no
                for link in self.topologydb.links.get(dpid, {}).values()
            }
            ports = sorted(
                p.port_no
                for p in getattr(switch, "ports", [])
                if p.port_no not in inter and p.port_no < of.OFPP_MAX
            )
            if dpid == src_dpid:
                ports = [p for p in ports if p != src_in_port]
            if not ports:
                continue
            actions = tuple(of.ActionOutput(p) for p in ports)
            self.southbound.packet_out(dpid, of.PacketOut(data=pkt, actions=actions))

    # -- discovery ingest + utilization hygiene ---------------------------

    def _link_add(self, event) -> None:
        link = event.link
        self.topologydb.add_link(link)
        self._link_rev[(link.dst.dpid, link.dst.port_no)] = (
            link.src.dpid, link.src.port_no,
        )

    def _link_delete(self, event) -> None:
        link = event.link
        self.topologydb.delete_link(link)
        self._link_rev.pop((link.dst.dpid, link.dst.port_no), None)
        self._drop_util((link.src.dpid, link.src.port_no))

    def _switch_leave(self, event) -> None:
        dpid = event.switch.dp.id
        # a southbound that only reports the disconnect (a real OF
        # channel drop, control/southbound.py) leaves the dead switch's
        # links in the DB — prune them through the normal delete events
        # so the RPC mirror and flow revalidation fire. The simulated
        # fabric already published these (control/fabric.py
        # remove_switch), in which case nothing is left to prune.
        self._prune_links(
            lambda link: dpid in (link.src.dpid, link.dst.dpid)
        )
        self.topologydb.delete_switch(event.switch)
        for key in [k for k in self.link_util if k[0] == dpid]:
            self._drop_util(key)
        self._link_rev = {
            d: s for d, s in self._link_rev.items()
            if d[0] != dpid and s[0] != dpid
        }

    def _port_delete(self, event) -> None:
        """A port died (real southbound's OFPT_PORT_STATUS delete /
        link-down): prune every link riding it, and drop it from the
        switch's port set — a dead port with no links would otherwise
        read as an edge port and receive every broadcast."""
        key = (event.dpid, event.port_no)
        self._prune_links(
            lambda link: (link.src.dpid, link.src.port_no) == key
            or (link.dst.dpid, link.dst.port_no) == key
        )
        self._drop_util(key)
        sw = self.topologydb.switches.get(event.dpid)
        if sw is not None:
            from sdnmpi_tpu.core.topology_db import Switch

            self.topologydb.add_switch(Switch.make(
                event.dpid,
                [p for p in sw.ports if p.port_no != event.port_no],
            ))

    def _prune_links(self, dead) -> None:
        stale = [
            link
            for dst_map in self.topologydb.links.values()
            for link in dst_map.values()
            if dead(link)
        ]
        for link in stale:
            self.bus.publish(ev.EventLinkDelete(link))
        if stale:
            self.bus.publish(ev.EventTopologyChanged())

    def _drop_util(self, key: tuple[int, int]) -> None:
        self.link_util.pop(key, None)
        self._tx_util.pop(key, None)
        self._rx_util.pop(key, None)
        if self.util_plane is not None:
            # staged-but-unflushed samples die with the link; the
            # device slot itself is zeroed through the delta-log repair
            # seam on the plane's next sync
            self.util_plane.drop(key)

    # -- utilization ingest -----------------------------------------------

    def routing_util(self):
        """The utilization input the oracle receives: the device plane
        when enabled, the raw host dict otherwise."""
        return self.util_plane if self.util_plane is not None else self.link_util

    def restore_link_util(self, samples: dict[tuple[int, int], float]) -> None:
        """Checkpoint restore: seed the host dict AND stage the device
        plane, so a resumed controller routes on warm utilization
        without waiting a Monitor interval."""
        self.link_util.update(samples)
        if self.util_plane is not None:
            for key, bps in samples.items():
                self.util_plane.stage(key, bps)

    def _stats_flush(self, event: ev.EventStatsFlush) -> None:
        """Monitor end-of-pass edge: one vectorized scatter of the
        pass's staged samples into the device plane, then one jitted
        congestion-analytics pass over the published epoch. Before the
        plane is bound (no routing call has built tensors yet) samples
        simply stay staged — the first base-cost evaluation flushes
        them. Under ``Config.hier_oracle`` there deliberately IS no
        device plane (the dense [V, V] tensor is the ceiling hier
        escapes) — the congestion report is served from the same host
        sample dict the hier composer steers on instead of staying
        silently empty (ISSUE 14 satellite)."""
        p = self.util_plane
        if p is not None:
            if p.sync(self.topologydb):
                p.flush()
                self._refresh_congestion()
        elif (
            self.config.hier_oracle
            and self.config.util_plane
            and self.config.oracle_backend == "jax"
        ):
            self._refresh_congestion_host()

    def _congestion_report(
        self, req: ev.CongestionReportRequest
    ) -> ev.CongestionReportReply:
        return ev.CongestionReportReply(self.congestion)

    def _refresh_congestion(self) -> None:
        """Device-side congestion analytics (ISSUE 7), one pass per
        flush: top-k hot links (jitted top-k over the published [V*V]
        snapshot — fixed shape, zero recompiles across churn), the
        per-collective attribution (which installed collectives' blocks
        ride those links, via the install-time directed-link index),
        and the oracle's discrete-vs-fractional congestion figures."""
        p = self.util_plane
        if p is None or not p.bound:
            return
        hot = p.hot_links(self.config.congestion_topk)
        _m_host_sampled.set(0.0)
        self.congestion = self._assemble_congestion(hot, epoch=p.epoch)

    def _refresh_congestion_host(self) -> None:
        """Congestion analytics under the hierarchical oracle (ISSUE 14
        satellite): hier deliberately skips the dense device UtilPlane,
        so the top-k pass runs over the Monitor's HOST sample dict —
        the exact view the hier composer's border steering consumes —
        and the report additionally aggregates per POD (the granularity
        hier routes at). The dict is host-sized (one entry per live
        directed link), so a host sort is the right tool here; the
        report shape matches the device path's, plus ``pods`` and
        ``source`` so consumers can tell which pass served it."""
        samples = self.link_util
        if not samples:
            return
        import heapq

        db = self.topologydb
        k = max(1, int(self.config.congestion_topk))
        # O(E log k) selection, and the dst side resolves only for the
        # k winners by scanning their OWN switch's link dict — never an
        # O(E) map rebuild per flush (hier exists for 65k-switch
        # fabrics; this runs on every Monitor pass)
        top = heapq.nlargest(k, samples.items(), key=lambda kv: kv[1])
        hot = [
            {
                "src": dpid,
                "dst": next(
                    (d for d, link in db.links.get(dpid, {}).items()
                     if link.src.port_no == port),
                    -1,
                ),
                "port": port, "bps": float(bps),
            }
            for (dpid, port), bps in top
            if bps > 0.0
        ]
        # pod aggregation: per-pod egress load (the per-switch sums the
        # hier steering folds, aggregated one level up), hottest first.
        # The PodMap is the DB's annotation when the generator emitted
        # one, else the partitioner map the hier oracle resolved at its
        # last refresh (discovered fabrics); before any refresh there
        # is no pod structure yet and the block is skipped.
        pods: list[dict] = []
        podmap = getattr(db, "podmap", None)
        if podmap is None:
            oracle = getattr(db, "_oracle", None)
            podmap = getattr(
                getattr(oracle, "_hier", None), "podmap", None
            )
        if podmap is not None:
            by_pod: dict[int, float] = {}
            for (dpid, _port), bps in samples.items():
                pod = podmap.pod_of.get(dpid)
                if pod is not None and bps > 0.0:
                    by_pod[pod] = by_pod.get(pod, 0.0) + float(bps)
            pods = [
                {"pod": p, "bps": round(v, 3)}
                for p, v in sorted(by_pod.items(), key=lambda kv: -kv[1])
            ][:k]
        _m_host_sampled.set(1.0)
        report = self._assemble_congestion(hot, epoch=0)
        report["source"] = "host_samples"
        if pods:
            report["pods"] = pods
        self.congestion = report

    def _assemble_congestion(self, hot: list[dict], epoch: int) -> dict:
        """Assemble the congestion block from decoded top-k entries:
        headline gauges, per-collective (and per-phase) attribution
        through the install-time link index, and the oracle's same-
        batch discrete/fractional figures. Shared by the device top-k
        pass and the hier host-sample pass so the two report shapes
        can never drift."""
        _m_hot_bps.set(hot[0]["bps"] if hot else 0.0)
        colls: list[dict] = []
        if hot:
            try:
                table = self.bus.request(
                    ev.CurrentCollectivesRequest()
                ).collectives
            except LookupError:
                table = ()  # minimal stacks without a Router
            hot_keys = {(h["src"], h["dst"]): h["bps"] for h in hot}
            for install in table:
                if not install.links:
                    continue
                ride = [k for k in hot_keys if k in install.links]
                if ride:
                    entry = {
                        "cookie": install.cookie,
                        "coll_type": install.coll_type,
                        "n_pairs": install.n_pairs,
                        "hot_links": len(ride),
                        "bps": sum(hot_keys[k] for k in ride),
                    }
                    # phase-grain attribution (ISSUE 8): a scheduled
                    # install resolves the hot link not just to the
                    # collective but to the PHASE(S) riding it
                    if install.phase_links is not None:
                        phases = sorted({
                            p for k in ride
                            for p in install.phase_links.get(k, ())
                        })
                        entry["n_phases"] = install.n_phases
                        entry["phases"] = phases
                    colls.append(entry)
            colls.sort(key=lambda c: -c["bps"])
        _m_hot_collectives.set(len(colls))
        oracle = getattr(self.topologydb, "_oracle", None)
        report = {
            "epoch": epoch,
            "top": hot,
            "collectives": colls,
            "discrete_max": getattr(
                oracle, "last_discrete_congestion", 0.0
            ),
            "fractional_max": getattr(
                oracle, "last_fractional_congestion", 0.0
            ),
            # the oracle only records a ratio when both figures came
            # from the SAME DAG-balanced batch — recomputing it here
            # would pair a later shortest/greedy pass's discrete figure
            # with a stale fractional bound
            "ratio": getattr(oracle, "last_congestion_ratio", 0.0),
        }
        if self.audit is not None:
            # measured-vs-modeled (ISSUE 15): the audit plane's per-flow
            # byte attribution beside every install's modeled congestion
            # — the fabric's observed truth against the scheduler's model
            report["measured"] = self.audit.report()
        return report

    def _port_stats(self, event: ev.EventPortStats) -> None:
        key = (event.dpid, event.port_no)
        self._tx_util[key] = event.tx_bps
        self._refresh_util(key)
        # the rx counter of this port measures the link ARRIVING here;
        # credit it to that link's source side (reference rx logging:
        # sdnmpi/monitor.py:79-88)
        src = self._link_rev.get(key)
        if src is not None:
            self._rx_util[src] = event.rx_bps
            self._refresh_util(src)

    def _refresh_util(self, key: tuple[int, int]) -> None:
        value = max(
            self._tx_util.get(key, 0.0), self._rx_util.get(key, 0.0)
        )
        self.link_util[key] = value
        if self.util_plane is not None:
            self.util_plane.stage(key, value)
