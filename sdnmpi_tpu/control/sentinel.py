"""Shadow route-quality sentinel: do the routes we installed still fit
the traffic we actually carry?

The audit plane (PR 15) answers "does the fabric hold the rows I
installed?"; this module answers the next question up the stack. Routes
are chosen against the *modeled* load at install time, but the measured
matrix (oracle/trafficplane.py) keeps moving — a tenant's collective
finishes, a serving burst shifts pods, and yesterday's balanced
assignment quietly concentrates today's bytes onto one uplink. RAMP
(arxiv 2211.15226) frames reconfiguration around exactly this
measured-vs-provisioned gap; the sentinel is the detector that tells
the (future) co-optimization PR *when* the gap opened and *where*.

Per stats flush (after the audit sweep feeds the matrix and the
TrafficPlane publishes):

- A paced round-robin sample of installed non-collective (src, dst)
  pairs (``Config.sentinel_sample_per_flush``; 0 = the whole installed
  population) is weighted by the published measured matrix. A sweep
  with no measured weight is free — gauges publish their healthy
  values and no dispatch runs.
- The **installed** path of each pair is reconstructed by walking the
  desired-flow store hop by hop over the live link table (the rows the
  controller believes are installed — the audit plane separately
  verifies the fabric agrees).
- A **fresh optimum** for the same pairs is computed through the
  oracle's balanced batch dispatch (topology_db.find_routes_batch_
  balanced), with the batch padded to the kernels/tiling pow2 ladder
  so shadow re-scoring compiles O(log samples) shapes total, never one
  per sample count (trace-count asserted in tests).
- The measured weights are projected onto both assignments:
  ``C_meas`` is the hottest link load under the installed paths,
  ``C_model`` under the fresh optimum, and
  ``measured_vs_modeled_divergence = C_meas / C_model`` (1.0 = the
  installed routes are as good as a fresh solve; 2.0 = the hottest
  link carries twice the bytes it needs to). ``route_staleness_ratio``
  is the fraction of sampled pairs whose installed walk is broken or
  longer than the fresh path.
- Divergence >= ``Config.sentinel_divergence_factor`` counts
  ``sentinel_divergence_total{tenant}`` — which the
  :class:`SentinelDivergence` flight trigger turns into a frozen
  bundle naming the worst (tenant, collective, pod-pair). Healing
  (re-driving the worst pair through the install plane) exists behind
  ``Config.sentinel_heal`` but defaults OFF: this channel observes;
  it does not mutate routing until a later PR opts in.
"""

from __future__ import annotations

import collections
import time
from typing import Optional

from sdnmpi_tpu.kernels.tiling import col_bucket
from sdnmpi_tpu.utils.metrics import REGISTRY

_m_staleness = REGISTRY.gauge(
    "route_staleness_ratio",
    "sampled installed routes broken or longer than a fresh optimum",
)
_m_divergence_gauge = REGISTRY.gauge(
    "measured_vs_modeled_divergence",
    "hottest measured link load: installed assignment / fresh optimum",
)
_m_sweeps = REGISTRY.counter(
    "sentinel_sweeps_total", "sentinel re-scoring sweeps"
)
_m_shadow = REGISTRY.counter(
    "sentinel_shadow_routes_total",
    "installed routes re-scored against a fresh oracle optimum",
)
_m_divergence = REGISTRY.labeled_counter(
    "sentinel_divergence_total", "tenant",
    "confirmed routes-don't-fit-the-traffic incidents per tenant",
)
_m_heals = REGISTRY.counter(
    "sentinel_heals_total",
    "worst diverging pairs re-driven through the install plane "
    "(Config.sentinel_heal opt-in)",
)
_m_heals_throttled = REGISTRY.counter(
    "sentinel_heals_throttled_total",
    "sentinel heals deferred because the tenant's admission token "
    "bucket was empty (the heal re-drive must not starve tenant "
    "serving traffic)",
)

#: hop bound for the installed-path walk — anything longer is a loop
_WALK_MAX = 64


class SentinelDivergence:
    """Flight-recorder trigger: any advance of the
    ``sentinel_divergence_total`` family freezes a bundle whose detail
    names the worst (tenant, collective, pod-pair) — the offered load
    no longer fits the installed routes."""

    name = "sentinel:divergence"

    def __init__(self, sentinel: "RouteSentinel") -> None:
        self.sentinel = sentinel

    @staticmethod
    def _total(snapshot: dict) -> int:
        pfx = "sentinel_divergence_total{"
        return sum(
            v for k, v in snapshot.get("counters", {}).items()
            if k.startswith(pfx)
        )

    def check(self, prev: dict, cur: dict, window=None) -> Optional[dict]:
        d = self._total(cur) - self._total(prev)
        if d <= 0:
            return None
        return {
            "divergences": int(d),
            "recent": self.sentinel.take_unreported(),
        }


class RouteSentinel:
    """Measured-traffic re-scoring of installed routes (module
    docstring). Single-threaded by bus discipline; ``sweep`` is the one
    entry point, driven per ``EventStatsFlush`` by the Controller after
    the audit sweep and the TrafficPlane flush."""

    def __init__(self, config, router, db, traffic, audit=None,
                 clock=time.monotonic) -> None:
        self.config = config
        self.router = router
        self.db = db
        self.traffic = traffic
        self.audit = audit
        self.clock = clock
        self._cursor = 0
        self.sweep_count = 0
        #: recent confirmed divergences (forensics context window)
        self.recent: collections.deque = collections.deque(maxlen=32)
        self._unreported: list[dict] = []
        #: last sweep's summary (forensics)
        self._last: dict = {}

    def trigger(self) -> SentinelDivergence:
        return SentinelDivergence(self)

    def take_unreported(self) -> list[dict]:
        out, self._unreported = self._unreported, []
        return out

    def forensics(self) -> dict:
        return {
            "sweeps": self.sweep_count,
            "last": dict(self._last),
            "recent_divergences": list(self.recent),
            "matrix": self.traffic.matrix(),
        }

    # -- sampling ----------------------------------------------------------

    def _population(self) -> list[tuple[str, str]]:
        """Sorted unique installed non-collective host pairs (collective
        rows are phase-schedule-owned — re-routing them pairwise would
        score the wrong objective)."""
        hosts = self.db.hosts
        seen = set()
        for table in self.router.recovery.desired.flows.values():
            for (src, dst), spec in table.items():
                if spec.collective:
                    continue
                if src in hosts and dst in hosts:
                    seen.add((src, dst))
        return sorted(seen)

    def _sample(self) -> list[tuple[str, str]]:
        rows = self._population()
        k = self.config.sentinel_sample_per_flush
        if not rows or k <= 0 or k >= len(rows):
            return rows
        start = self._cursor % len(rows)
        take = [rows[(start + i) % len(rows)] for i in range(k)]
        self._cursor = (start + k) % len(rows)
        return take

    # -- path reconstruction ----------------------------------------------

    def _hop_map(self) -> dict[tuple[int, int], int]:
        """(dpid, out_port) -> next dpid over the live link table; ports
        absent here deliver to hosts and end the walk."""
        out: dict[tuple[int, int], int] = {}
        for src, dst_map in self.db.links.items():
            for dst, link in dst_map.items():
                out[(src, link.src.port_no)] = dst
        return out

    def _installed_links(
        self, src: str, dst: str, hop_map: dict
    ) -> Optional[list[tuple[int, int]]]:
        """Fabric links ((dpid, out_port) per hop, host delivery
        excluded) of the pair's installed path per the desired store;
        None when the walk is broken (missing row, loop, wrong edge)."""
        flows = self.router.recovery.desired.flows
        src_host = self.db.hosts.get(src)
        dst_host = self.db.hosts.get(dst)
        if src_host is None or dst_host is None:
            return None
        cur = src_host.port.dpid
        links: list[tuple[int, int]] = []
        for _ in range(_WALK_MAX):
            spec = flows.get(cur, {}).get((src, dst))
            if spec is None:
                return None
            nxt = hop_map.get((cur, spec.out_port))
            if nxt is None:
                # host delivery port: the walk is complete iff we are
                # standing at the destination's edge switch
                return links if cur == dst_host.port.dpid else None
            links.append((cur, spec.out_port))
            cur = nxt
        return None

    def _shadow_links(
        self, pairs: list[tuple[str, str]], hop_map: dict
    ) -> list[list[tuple[int, int]]]:
        """Fresh balanced assignment for the sampled pairs, padded to
        the pow2 bucket ladder so the device dispatch compiles O(log
        samples) shapes (the final host hop of each fdb is dropped —
        only fabric links carry projected load)."""
        n = len(pairs)
        bucket = col_bucket(n, 4096)
        padded = list(pairs) + [pairs[-1]] * (bucket - n)
        fdbs, _ = self.db.find_routes_batch_balanced(padded)
        out = []
        for fdb in fdbs[:n]:
            out.append([hop for hop in fdb if hop in hop_map])
        return out

    # -- sweep -------------------------------------------------------------

    def sweep(self, now: Optional[float] = None) -> dict:
        _m_sweeps.inc()
        self.sweep_count += 1
        pairs = self._sample()
        weights = [self.traffic.pair_bps(s, d) for s, d in pairs]
        if not pairs or not any(w > 0.0 for w in weights):
            # nothing measured to score against: healthy gauges, no
            # dispatch — steady tests without data-plane traffic pay a
            # dict scan, not a device solve
            _m_staleness.set(0.0)
            _m_divergence_gauge.set(1.0)
            self._last = {"sampled": len(pairs), "weighted": 0}
            return self._last
        hop_map = self._hop_map()
        installed = [self._installed_links(s, d, hop_map) for s, d in pairs]
        fresh = self._shadow_links(pairs, hop_map)
        _m_shadow.inc(len(pairs))

        stale = 0
        meas_load: dict[tuple[int, int], float] = {}
        model_load: dict[tuple[int, int], float] = {}
        for i, (inst, opt) in enumerate(zip(installed, fresh)):
            if inst is None or len(inst) > len(opt):
                stale += 1
            if inst is None:
                # a broken pair cannot be projected fairly; staleness
                # carries the signal, load comparison skips it
                continue
            w = weights[i]
            if w <= 0.0:
                continue
            for link in inst:
                meas_load[link] = meas_load.get(link, 0.0) + w
            for link in opt:
                model_load[link] = model_load.get(link, 0.0) + w
        c_meas = max(meas_load.values(), default=0.0)
        c_model = max(model_load.values(), default=0.0)
        divergence = (c_meas / c_model) if c_model > 0.0 else 1.0
        staleness = stale / len(pairs)
        _m_staleness.set(staleness)
        _m_divergence_gauge.set(divergence)
        self._last = {
            "sampled": len(pairs),
            "weighted": sum(1 for w in weights if w > 0.0),
            "stale": stale,
            "c_measured": c_meas,
            "c_modeled": c_model,
            "divergence": divergence,
        }
        if divergence >= self.config.sentinel_divergence_factor:
            self._confirm(pairs, weights, installed, meas_load, divergence,
                          staleness, c_meas, c_model)
        return self._last

    # -- confirmation ------------------------------------------------------

    def _confirm(self, pairs, weights, installed, meas_load, divergence,
                 staleness, c_meas, c_model) -> None:
        hot_link = max(meas_load, key=meas_load.get)
        worst_i, worst_w = None, -1.0
        for i, inst in enumerate(installed):
            if inst and hot_link in inst and weights[i] > worst_w:
                worst_i, worst_w = i, weights[i]
        if worst_i is None:
            return
        src, dst = pairs[worst_i]
        tenant = self.router.admission._tenants.get(src, "-")
        detail = {
            "divergence": divergence,
            "factor": self.config.sentinel_divergence_factor,
            "staleness": staleness,
            "c_measured": c_meas,
            "c_modeled": c_model,
            "hot_link": list(hot_link),
            "tenant": tenant,
            "pair": [src, dst],
            "pod_pair": [
                self.traffic.ep_name(src), self.traffic.ep_name(dst),
            ],
            "pair_bps": worst_w,
            "collective": self._worst_collective(),
        }
        _m_divergence.inc(tenant)
        self.recent.append(detail)
        self._unreported.append(detail)
        if self.config.sentinel_heal:
            # the heal re-drive spends the tenant's admission tokens
            # like any reactive route (ISSUE 20 satellite): a healing
            # storm competes with — never starves — serving traffic.
            # With no rate armed admit() is always True (unchanged).
            if self.router.admission.admit(src):
                self.router.reinstall_pairs([(src, dst)])
                _m_heals.inc()
            else:
                _m_heals_throttled.inc()

    def _worst_collective(self) -> Optional[int]:
        """Cookie of the collective moving the most measured bytes over
        the audit window, best-effort (None without an audit plane or
        when no collective carried traffic)."""
        if self.audit is None:
            return None
        try:
            report = self.audit.report()
        except Exception:
            return None
        best, best_bps = None, 0.0
        for entry in report.get("collectives", ()):
            bps = entry.get("measured_bps", 0.0)
            if bps > best_bps:
                best, best_bps = entry.get("cookie"), bps
        return best
