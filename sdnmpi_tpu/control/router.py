"""Router app — packet-in dispatcher and flow installer.

Equivalent of the reference's ``Router`` (reference: sdnmpi/router.py:37-195):
filters LLDP/broadcast/IPv6-multicast packet-ins, routes normal unicast via
``FindRouteRequest``, decodes SDN-MPI virtual MACs and resolves ranks for
MPI packets, installs one flow per hop with de-duplication against the
SwitchFDB, rewrites virtual -> real destination MAC on the last hop, sends
the triggering packet out of the ingress switch, and falls back to a
controlled broadcast when no route exists.

Upgrade over the reference: flow lifecycle management. The reference
installs permanent flows and never removes them (SURVEY §2 defects — stale
routes survive link failures and process exits). Here, topology mutations
trigger revalidation of every installed (src, dst) flow against a fresh
oracle batch — stale hops are deleted from the switches, surviving routes
are eagerly reinstalled along their new path — and an MPI process exit
tears down the flows addressed to its rank's virtual MAC.
"""

from __future__ import annotations

import dataclasses
import logging
import time

import numpy as np

from sdnmpi_tpu.config import Config, DEFAULT_CONFIG
from sdnmpi_tpu.control import events as ev
from sdnmpi_tpu.control.bus import EventBus
from sdnmpi_tpu.control.recovery import InstallVerdict, RecoveryPlane
from sdnmpi_tpu.core.collective_table import CollectiveInstall, CollectiveTable
from sdnmpi_tpu.core.switch_fdb import SwitchFDB
from sdnmpi_tpu.protocol import openflow as of
from sdnmpi_tpu.protocol.vmac import CollectiveType, VirtualMac, is_sdn_mpi_addr
from sdnmpi_tpu.utils.mac import BROADCAST_MAC, int_to_mac, is_ipv6_multicast
from sdnmpi_tpu.utils.metrics import LATENCY_BUCKETS_S, REGISTRY, SIZE_BUCKETS
from sdnmpi_tpu.utils.tracing import NULL_SPAN, start_span

log = logging.getLogger("Router")


def _mac_of(key: int, memo: dict) -> str:
    """Memoized ``int_to_mac`` — the ONE MAC-key materialization both
    the phased install (desired-store rows) and `_mac_rows`
    (teardown/rollback rows) go through, so the strings can never
    diverge and break the exact-match delete contract."""
    s = memo.get(key)
    if s is None:
        s = memo[key] = int_to_mac(key)
    return s


def _vmac_luts(
    coll_type: int, ranks_arr: np.ndarray, macs_str: list,
) -> tuple:
    """Member MAC keys + per-endpoint vMAC part LUTs for a collective —
    the ONE preamble the flat block install and the phased install
    share, so the encoding call shape can never drift between the legs
    (their flow tables must stay bit-identical on the differential).
    The LUTs come from the codec that owns the ABI
    (vmac = src_lut[si] | dst_lut[di]; the base byte is baked into
    both, OR-ing it twice is idempotent)."""
    from sdnmpi_tpu.protocol.vmac import encode_batch_ints
    from sdnmpi_tpu.utils.mac import macs_to_ints

    zero = np.zeros(len(ranks_arr), np.int64)
    return (
        macs_to_ints(macs_str),
        encode_batch_ints(coll_type, ranks_arr, zero),
        encode_batch_ints(coll_type, zero, ranks_arr),
    )


def _mac_rows(arr: np.ndarray, memo: dict) -> list:
    """Materialize a phased install's [N, 3] (dpid, src key, dst key)
    int rows back into the (dpid, src, dst) MAC-string rows
    ``_del_flows_window`` tears down by — ``memo`` is shared across
    phases so each distinct MAC key converts once."""
    return [
        (d, _mac_of(s, memo), _mac_of(t, memo))
        for d, s, t in zip(
            arr[:, 0].tolist(), arr[:, 1].tolist(), arr[:, 2].tolist()
        )
    ]

# -- pipeline telemetry (ISSUE 4): every stage of the route->install
# pipeline records into the process-wide registry; the RPC mirror and
# the Prometheus exposition read the same instruments.
_m_packet_ins = REGISTRY.counter(
    "router_packet_ins_total", "unicast/MPI packet-ins dispatched to routing"
)
_m_window_occupancy = REGISTRY.histogram(
    "coalescer_window_occupancy", SIZE_BUCKETS,
    "parked route lookups per flushed coalescer window",
)
_m_window_age = REGISTRY.histogram(
    "coalescer_window_age_seconds", LATENCY_BUCKETS_S,
    "park-to-window-cut age of each window's oldest member",
)
_m_queue_depth = REGISTRY.gauge(
    "coalescer_queue_depth", "route lookups parked right now"
)
_m_windows = REGISTRY.counter(
    "pipeline_windows_total", "route windows resolved (batched or serial)"
)
_m_inflight = REGISTRY.gauge(
    "pipeline_inflight_windows", "dispatched-but-unreaped route windows"
)
_m_reap_s = REGISTRY.histogram(
    "pipeline_reap_seconds", LATENCY_BUCKETS_S,
    "host blocked in RouteWindow.reap (device wait + decode)",
)
_m_install_s = REGISTRY.histogram(
    "pipeline_install_seconds", LATENCY_BUCKETS_S,
    "window FlowMod materialization + batched install",
)
_m_e2e_s = REGISTRY.histogram(
    "install_e2e_seconds", LATENCY_BUCKETS_S,
    "coalescer flush end-to-end (first dispatch -> last install) — the "
    "live twin of bench config 10's install_e2e_ms",
)
_m_overlap_gain = REGISTRY.gauge(
    "pipeline_overlap_gain",
    "serial-equivalent wall / end-to-end wall of the last flush burst "
    "(>1 means device compute overlapped host decode+install — the "
    "live twin of bench config 10's overlap_gain). The serial "
    "equivalent counts each window's in-flight interval (dispatch "
    "return -> reap start) as device time a serial pass would have "
    "waited out, so it is an upper-bound estimate: exact when the "
    "device is busy the whole interval, optimistic when it finished "
    "early",
)
_m_routed = REGISTRY.counter(
    "router_routes_resolved_total", "route lookups that found a path"
)
_m_unroutable = REGISTRY.counter(
    "router_routes_unroutable_total", "route lookups with no path"
)
_m_flows_installed = REGISTRY.counter(
    "router_flows_installed_total", "switch-level flow entries installed"
)
_m_flows_deleted = REGISTRY.counter(
    "router_flows_deleted_total", "switch-level flow entries torn down"
)
_m_teardown_batches = REGISTRY.counter(
    "router_teardown_batches_total",
    "revalidation/exit teardown bursts sent as batched OFPFC_DELETEs",
)
_m_revalidations = REGISTRY.counter(
    "router_revalidations_total", "flow revalidation passes that ran"
)
_m_revalidations_skipped = REGISTRY.counter(
    "router_revalidations_skipped_total",
    "revalidation passes skipped by the epoch gate",
)
_m_reval_drained = REGISTRY.counter(
    "router_reval_flows_drained_total",
    "re-scored flows whose NEW path moved off the dirtied switches "
    "entirely (the delta window's device-computed touched verdict): "
    "how much traffic a flap drains away from the failed region",
)
# delta-revalidation stage decomposition (ISSUE 7): the live twins of
# bench config 8's repair/rescore/diff/install medians — the repair
# stage is the oracle's own oracle_repair timing (utils/tracing.STATS);
# the three control-plane stages record here per chunk, with matching
# spans on BOTH the pipelined path and the serial / full-pass fallbacks
# so traces stay comparable across escape hatches.
_m_reval_rescore_s = REGISTRY.histogram(
    "reval_rescore_seconds", LATENCY_BUCKETS_S,
    "per-chunk re-scoring wall (delta dispatch -> window reaped)",
)
_m_reval_diff_s = REGISTRY.histogram(
    "reval_diff_seconds", LATENCY_BUCKETS_S,
    "per-chunk hop-diff wall (reaped window vs installed state)",
)
_m_reval_install_s = REGISTRY.histogram(
    "reval_install_seconds", LATENCY_BUCKETS_S,
    "per-chunk changed-span teardown + reinstall wall",
)
_m_reval_affected = REGISTRY.histogram(
    "reval_affected_flows", SIZE_BUCKETS,
    "flows re-scored per revalidation pass (the delta-narrowed blast "
    "radius; full passes count everything installed)",
)
_m_recovery_redrive_s = REGISTRY.histogram(
    "recovery_redrive_seconds", LATENCY_BUCKETS_S,
    "wall of one recovery re-drive (retry-queue pop: deletes + resync)",
)
# collective phase scheduler (ISSUE 8): phase progress of scheduled
# installs — the telemetry snapshot (and its RPC mirror) carries these
# beside the per-phase EventCollectivePhaseInstalled broadcasts.
_m_sched_programs = REGISTRY.counter(
    "sched_programs_total", "phased flow programs installed"
)
_m_sched_phases = REGISTRY.counter(
    "sched_phases_total", "collective phases installed (all programs)"
)
_m_sched_phase_install_s = REGISTRY.histogram(
    "sched_phase_install_seconds", LATENCY_BUCKETS_S,
    "one phase's reap + FlowMod materialization + batched install "
    "(phases k+1..K compute on device while this runs)",
)
_m_sched_completion = REGISTRY.gauge(
    "sched_program_completion",
    "modeled completion of the last scheduled program: sum over phases "
    "of the phase's discrete max-link load (phases serialize; the "
    "bottleneck link bounds each phase's duration) — the live twin of "
    "bench config 12's completion figure",
)
_m_sched_max_phase = REGISTRY.gauge(
    "sched_program_max_phase_congestion",
    "hottest single phase of the last scheduled program — the figure "
    "comparable to a flat install's max_congestion",
)


@dataclasses.dataclass
class _PendingRoute:
    """One packet-in's route lookup parked in the coalescer: the match
    pair, the true destination (MPI virtual-MAC flows), and everything
    needed to finish the packet's handling after the batched reply.
    ``span`` is the packet-in's root trace span (NULL_SPAN when tracing
    is off); ``park`` times the coalescer wait."""

    src: str
    dst: str  # match destination (virtual MAC for MPI flows)
    true_dst: str | None  # rewrite target; None = plain unicast
    dpid: int
    in_port: int
    pkt: of.Packet
    buffer_id: int
    span: object = NULL_SPAN
    park: object = NULL_SPAN
    #: monotonic park time — each flushed window's age histogram sample
    #: is measured from ITS oldest member, not the queue's first park
    t_parked: float = 0.0
    #: coalescer class (ISSUE 11): True for collective-member MPI
    #: lookups (an alltoall storm's per-pair packet-ins), False for
    #: latency-sensitive traffic (plain unicast, MPI point-to-point).
    #: Window composition takes latency-sensitive entries first, so a
    #: bulk storm parks BEHIND the pairs users are waiting on.
    bulk: bool = False


class Router:
    name = "Router"

    def __init__(
        self,
        bus: EventBus,
        southbound,
        config: Config = DEFAULT_CONFIG,
    ) -> None:
        self.bus = bus
        self.southbound = southbound
        self.config = config
        self.fdb = SwitchFDB()
        #: block-installed collectives (array-native proactive path)
        self.collectives = CollectiveTable()
        #: live datapaths (reference: router.py:69-81 keeps self.dps)
        self.dps: set[int] = set()
        #: route-request coalescer (Config.coalesce_routes): packet-in
        #: lookups park here and resolve as ONE padded batched oracle
        #: call per flush instead of one device dispatch each — the
        #: device round-trip amortizes across the burst, and the padded
        #: batch rides the oracle's bucketed jit cache. The live switch
        #: is this attribute, not the config flag: the composition root
        #: (Controller) arms it only when the southbound provides an
        #: idle edge to flush from, so a lone packet can never strand
        #: in the queue waiting for a companion that never comes.
        self.coalesce: bool = False
        self._pending: list[_PendingRoute] = []
        self._pending_t0 = 0.0
        self._flushing = False
        #: revalidation epoch gate: the TopologyDB version and UtilPlane
        #: epoch as of the last completed revalidation pass. A repeat
        #: EventTopologyChanged with neither advanced is a no-op, and
        #: the delta log between passes narrows re-routing to the flows
        #: whose installed paths touch a dirtied switch.
        self._reval_version: int | None = None
        self._reval_util_epoch: int = -1
        #: failure-domain recovery plane (ISSUE 5): desired-flow store,
        #: pending-barrier table, bounded retry queue. The store is
        #: always maintained (it is just bookkeeping); the reconcile /
        #: retry / anti-entropy behaviors gate on Config.recovery_plane.
        self.recovery = RecoveryPlane(config)
        self.recovery.on_exhausted = self._resync_datapath
        #: per-tenant admission gate (ISSUE 11, control/admission.py):
        #: every packet-in passes through it BEFORE any routing work.
        #: Config.admission_rate == 0 (the default) admits everything.
        from sdnmpi_tpu.control.admission import AdmissionControl

        self.admission = AdmissionControl(
            config.admission_rate, config.admission_burst
        )
        #: SLO plane (ISSUE 14, control/slo.py): set by the Controller
        #: when Config.slo_targets is non-empty. None (the default)
        #: keeps the per-window cost at one attribute load + is-None
        #: test — the PR-4/7 unarmed hot-path contract.
        self.slo = None
        #: fabric audit plane (ISSUE 15, control/audit.py): set by the
        #: Controller when the southbound can answer flow stats. The
        #: Router only ever asks it to verify a wiped switch.
        self.audit = None
        #: rate-shaped reconcile (ISSUE 15 satellite, carried from
        #: PR 5): datapath-up reconciles past
        #: Config.reconcile_max_per_flush park here (FIFO) and drain on
        #: following recovery ticks — a power-cycled pod redialing at
        #: once must not re-drive every desired set in one burst
        self._reconcile_pending: list[int] = []
        self._reconcile_spent = 0
        #: jitter-deferred wipe-resync republishes (ISSUE 20
        #: satellite): (due, dpid) pairs drained by recovery_tick
        self._resync_due: list[tuple[float, int]] = []

        bus.subscribe(ev.EventDatapathUp, self._datapath_up)
        bus.subscribe(ev.EventDatapathDown, self._datapath_down)
        bus.subscribe(ev.EventBarrierAck, lambda e: self.recovery.ack(e.dpid, e.xid))
        bus.subscribe(ev.EventStatsFlush, lambda e: self.recovery_tick())
        bus.subscribe(ev.EventPacketIn, self._packet_in)
        bus.subscribe(ev.EventTopologyChanged, lambda e: self._revalidate_flows())
        bus.subscribe(ev.EventProcessDelete, self._process_delete)
        bus.subscribe(ev.EventFlowRemoved, self._flow_removed)
        bus.provide(ev.CurrentFDBRequest, self._current_fdb)
        bus.provide(ev.CurrentCollectivesRequest, self._current_collectives)

    # -- flow plumbing ----------------------------------------------------

    def _add_flow(
        self,
        dpid: int,
        src: str,
        dst: str,
        out_port: int,
        actions: tuple[of.Action, ...] = (),
    ):
        # match on (dl_src, dl_dst) exactly like the reference
        # (router.py:49-62); for MPI flows dst is the *virtual* MAC so the
        # whole path forwards on it and only the last hop rewrites
        mod = of.FlowMod(
            match=of.Match(dl_src=src, dl_dst=dst),
            actions=actions + (of.ActionOutput(out_port),),
            priority=self.config.priority_default,
            idle_timeout=self.config.flow_idle_timeout,
            hard_timeout=self.config.flow_hard_timeout,
        )
        return self.southbound.flow_mod(dpid, mod)

    def _send_window(self, kd, burst: of.FlowModBatch):
        """Ship one dpid-grouped FlowModBatch through the richest send
        entry point the southbound offers (whole-window byte spans >
        per-switch batches). Returns the southbound's
        :class:`InstallVerdict`, or None for duck-typed southbounds
        without the verdict contract (the fire-and-forget legacy, which
        the recovery plane treats as a no-op)."""
        window_send = getattr(self.southbound, "flow_mods_window", None)
        if window_send is not None:
            # one batched encode for the whole window; each switch
            # gets its contiguous byte span (southbound slices it)
            return window_send(kd, burst)
        from sdnmpi_tpu.utils.arrays import group_spans

        verdict = None
        for lo, hi in group_spans(kd):
            v = self.southbound.flow_mods_batch(
                int(kd[lo]), of.FlowModBatch(
                    src=burst.src[lo:hi],
                    dst=burst.dst[lo:hi],
                    out_port=burst.out_port[lo:hi],
                    rewrite=(
                        None if burst.rewrite is None
                        else burst.rewrite[lo:hi]
                    ),
                    priority=burst.priority,
                    idle_timeout=burst.idle_timeout,
                    hard_timeout=burst.hard_timeout,
                    command=burst.command,
                )
            )
            if isinstance(v, InstallVerdict):
                if verdict is None:
                    verdict = InstallVerdict()
                verdict.sent += v.sent
                verdict.dropped += v.dropped
                verdict.barriers += v.barriers
        return verdict

    def _del_flow(self, dpid: int, src: str, dst: str):
        mod = of.FlowMod(
            match=of.Match(dl_src=src, dl_dst=dst),
            actions=(),
            priority=self.config.priority_default,
            command=of.OFPFC_DELETE,
        )
        _m_flows_deleted.inc()
        return self.southbound.flow_mod(dpid, mod)

    def _del_flows_window(self, rows: list[tuple[int, str, str]]) -> None:
        """Tear down a burst of (dpid, src, dst) exact matches through
        the PR-3 window installer: the whole burst materializes as ONE
        ``OFPFC_DELETE`` :class:`~sdnmpi_tpu.protocol.openflow.FlowModBatch`
        and serializes in one batched wire encode
        (``encode_flow_mods_spans`` — the encoder always supported the
        command; this is the first caller), with each switch receiving
        its contiguous byte span. Revalidation after a link flap and
        rank-exit teardowns are delete *storms* — per-mod scalar
        encodes cost what the PR-3 install batching already eliminated
        on the add side. Dead datapaths are skipped (same rule as the
        scalar leg); ``pipelined_install=False`` or a batchless
        southbound falls back to scalar ``_del_flow`` per row — the
        differential escape hatch, byte-identical on the wire."""
        # the rows leave the DESIRED store unconditionally (dead-dpid
        # rows too: a crashed switch's redial must not resurrect them)
        for dpid, src, dst in rows:
            self.recovery.desired.remove(dpid, src, dst)
        live = [r for r in rows if r[0] in self.dps]
        if not live:
            return
        if (
            not self.config.pipelined_install
            or not hasattr(self.southbound, "flow_mods_batch")
        ):
            failed: dict[int, set] = {}
            for dpid, src, dst in live:
                if self._del_flow(dpid, src, dst) is False:
                    failed.setdefault(dpid, set()).add((src, dst))
            if failed and self.config.recovery_plane:
                self.recovery.note_send(
                    InstallVerdict(dropped=sorted(failed)),
                    delete_rows=failed,
                )
            return

        from sdnmpi_tpu.utils.mac import macs_to_ints

        kd = np.array([r[0] for r in live], np.int64)
        order = np.argsort(kd, kind="stable")
        kd = kd[order]
        burst = of.FlowModBatch(
            src=macs_to_ints([r[1] for r in live])[order],
            dst=macs_to_ints([r[2] for r in live])[order],
            out_port=np.zeros(len(live), np.int32),  # DELETE: no actions
            rewrite=None,
            priority=self.config.priority_default,
            command=of.OFPFC_DELETE,
        )
        _m_flows_deleted.inc(len(live))
        _m_teardown_batches.inc()
        verdict = self._send_window(kd, burst)
        if self.config.recovery_plane:
            # a dropped teardown re-drives as a teardown (not a resync):
            # the retry entry carries the exact (src, dst) rows
            delete_rows: dict[int, set] = {}
            for dpid, src, dst in live:
                delete_rows.setdefault(dpid, set()).add((src, dst))
            self.recovery.note_send(verdict, delete_rows=delete_rows)

    def _add_flows_for_path(
        self,
        fdb: list[tuple[int, int]],
        src: str,
        dst: str,
        true_dst: str | None = None,
    ) -> None:
        """Install one flow per hop (reference: router.py:83-104)."""
        failed: list[int] = []
        for idx, (dpid, out_port) in enumerate(fdb):
            if self.fdb.exists(dpid, src, dst):
                continue
            if dpid not in self.dps:
                # don't record hops we couldn't install: recording them
                # would dedup-suppress the install forever once the
                # datapath returns
                continue
            self.fdb.update(dpid, src, dst, out_port)
            _m_flows_installed.inc()
            self.bus.publish(ev.EventFDBUpdate(dpid, src, dst, out_port))

            last = idx == len(fdb) - 1
            rewrite = true_dst if (true_dst and last) else None
            self.recovery.desired.record(dpid, src, dst, out_port, rewrite)
            if rewrite:
                # virtual -> real MAC rewrite on the final hop
                # (reference: router.py:98-102)
                ok = self._add_flow(
                    dpid, src, dst, out_port, (of.ActionSetDlDst(true_dst),)
                )
            else:
                ok = self._add_flow(dpid, src, dst, out_port)
            if ok is False:
                failed.append(dpid)
        if failed and self.config.recovery_plane:
            # dropped scalar installs enter the same bounded retry queue
            # the batched windows use (resync re-drives the desired set)
            self.recovery.note_send(
                InstallVerdict(dropped=sorted(set(failed)))
            )

    def _send_packet_out(
        self,
        fdb: list[tuple[int, int]],
        dpid: int,
        pkt: of.Packet,
        buffer_id: int = of.OFP_NO_BUFFER,
    ) -> None:
        """Emit the triggering packet from the ingress switch only,
        reusing the switch-side buffer when the packet-in carried one —
        the frame is not re-sent over the control channel (reference:
        router.py:106-123, buffer handling at :111-118)."""
        for entry_dpid, out_port in fdb:
            if entry_dpid == dpid:
                buffered = buffer_id != of.OFP_NO_BUFFER
                out = of.PacketOut(
                    data=None if buffered else pkt,
                    actions=(of.ActionOutput(out_port),),
                    buffer_id=buffer_id,
                )
                self.southbound.packet_out(dpid, out)
                break

    # -- packet-in dispatch (reference: router.py:125-160) ----------------

    def _packet_in(self, event: ev.EventPacketIn) -> None:
        pkt = event.pkt
        src, dst = pkt.eth_src, pkt.eth_dst

        if pkt.eth_type == of.ETH_TYPE_LLDP:
            return
        if dst == BROADCAST_MAC:
            return  # broadcasts are the TopologyManager's job
        if is_ipv6_multicast(dst):
            return
        if is_sdn_mpi_addr(dst):
            return self._mpi_packet_in(event)

        if not self.admission.admit(src):
            return  # over the tenant's admitted rate: drop at the door

        log.info("Packet in at %s (%s) %s -> %s", event.dpid, event.in_port, src, dst)

        _m_packet_ins.inc()
        sp = start_span(
            "packet_in", dpid=event.dpid, in_port=event.in_port,
            src=src, dst=dst,
        )
        if self.coalesce:
            return self._enqueue_route(src, dst, None, event, span=sp)
        fdb = self.bus.request(ev.FindRouteRequest(src, dst)).fdb
        if fdb:
            _m_routed.inc()
            self._add_flows_for_path(fdb, src, dst)
            self._send_packet_out(fdb, event.dpid, pkt, event.buffer_id)
        else:
            _m_unroutable.inc()
            self.bus.request(ev.BroadcastRequest(pkt, event.dpid, event.in_port))
        sp.end(routable=bool(fdb))

    # -- MPI packets (reference: router.py:166-195) -----------------------

    def _mpi_packet_in(self, event: ev.EventPacketIn) -> None:
        pkt = event.pkt
        if not self.admission.admit(pkt.eth_src):
            # over the tenant's admitted rate: drop at the door — before
            # the vMAC decode, the per-packet log line, rank resolution
            # or any other per-request work, so a storm of rejects
            # costs the control loop near nothing
            return
        vmac = VirtualMac.decode(pkt.eth_dst)
        log.info(
            "SDNMPI communication from rank %s to rank %s (collective %s)",
            vmac.src_rank,
            vmac.dst_rank,
            vmac.coll_type,
        )

        true_dst = self.bus.request(ev.RankResolutionRequest(vmac.dst_rank)).mac
        if not true_dst:
            return  # unresolved rank -> drop (reference: router.py:186-187)

        _m_packet_ins.inc()
        sp = start_span(
            "packet_in", dpid=event.dpid, in_port=event.in_port,
            src=pkt.eth_src, dst=pkt.eth_dst, mpi=True,
        )
        # collective-member lookups are the BULK coalescer class: a
        # storm of them must not starve latency-sensitive singles
        bulk = vmac.coll_type != CollectiveType.P2P
        if self.coalesce:
            self._enqueue_route(
                pkt.eth_src, pkt.eth_dst, true_dst, event, span=sp,
                bulk=bulk,
            )
        else:
            fdb = self.bus.request(ev.FindRouteRequest(pkt.eth_src, true_dst)).fdb
            if fdb:
                _m_routed.inc()
                self._add_flows_for_path(fdb, pkt.eth_src, pkt.eth_dst, true_dst)
                self._send_packet_out(fdb, event.dpid, pkt, event.buffer_id)
            else:
                _m_unroutable.inc()
            sp.end(routable=bool(fdb))

        if self.config.proactive_collectives and vmac.coll_type != CollectiveType.P2P:
            self._install_collective(vmac)

    # -- route-request coalescing (no reference equivalent) ---------------

    def _enqueue_route(
        self, src: str, dst: str, true_dst: str | None,
        event: ev.EventPacketIn, span=NULL_SPAN, bulk: bool = False,
    ) -> None:
        """Park one packet-in's route lookup for batched resolution.

        Flush triggers: the pending batch reaching
        ``Config.coalesce_max_batch``, or ``Config.coalesce_window_s``
        elapsing since the batch opened. The southbound's idle edge
        (Fabric.on_idle -> :meth:`flush_routes`) bounds the wait: a
        burst is always resolved before control returns to the caller
        that injected it, so coalescing never strands a packet."""
        now = time.monotonic()
        if not self._pending:
            self._pending_t0 = now
        self._pending.append(_PendingRoute(
            src, dst, true_dst, event.dpid, event.in_port, event.pkt,
            event.buffer_id, span=span, park=span.child("coalesce_park"),
            t_parked=now, bulk=bulk,
        ))
        _m_queue_depth.set(len(self._pending))
        if not self._flushing and (
            len(self._pending) >= self.config.coalesce_max_batch
            or time.monotonic() - self._pending_t0
            >= self.config.coalesce_window_s
        ):
            self.flush_routes()

    def flush_routes(self) -> None:
        """Resolve every pending route lookup, one batched oracle call
        per ``coalesce_max_batch`` slice, then finish each parked packet
        exactly as the direct path would (install + packet-out, or
        controlled broadcast for routeless unicast). Loops until the
        queue drains: packet-outs re-entering the data plane may park
        new lookups mid-flush.

        With ``Config.pipelined_install`` the windows are
        *double-buffered* through the oracle's split-phase API
        (DispatchRoutesBatchRequest): window k+1's device program is
        dispatched BEFORE window k is reaped, so k+1 computes on device
        while the host decodes, materializes, and installs k — the
        device never idles between windows of a burst. Install order is
        preserved (k always installs before k+1 is reaped)."""
        if self._flushing or not self._pending:
            # idle edges fire constantly; an empty flush must not
            # observe a meaningless e2e sample
            return
        self._flushing = True
        t_flush0 = time.perf_counter()
        stage_wall = 0.0  # dispatch + reap + install walls
        hidden_wall = 0.0  # in-flight device intervals the host overlapped
        last_window_span = 0  # e2e exemplar: the burst's last window

        def _reap_timed(batch, handle, wsp, t_dispatched):
            """Reap window ``handle`` (timed, spanned) and finish its
            batch. The interval between the window's dispatch return
            and this reap is device compute the host overlapped with
            other work — a serial pass would have waited it out, so it
            feeds the overlap-gain numerator."""
            nonlocal stage_wall, hidden_wall
            t0 = time.perf_counter()
            hidden_wall += t0 - t_dispatched
            rsp = wsp.child("reap")
            try:
                wr = handle.reap()
            finally:
                # a raising reap (device error surfacing through the
                # window) must not leave the in-flight gauge pinned or
                # the spans open — the controller outlives the window
                rsp.end()
                dt = time.perf_counter() - t0
                _m_reap_s.observe(dt)
                _m_inflight.dec()
            t0 = time.perf_counter()
            try:
                self._finish_batch(batch, wr, wsp)
            finally:
                wsp.end()
                stage_wall += dt + (time.perf_counter() - t0)

        try:
            prev: tuple | None = None  # (batch, window, wsp, t_dispatched)
            while self._pending or prev is not None:
                batch = self._next_window()
                _m_queue_depth.set(len(self._pending))
                window = None
                wsp = NULL_SPAN
                if batch:
                    _m_window_occupancy.observe(len(batch))
                    # age of THIS window's oldest member (not the whole
                    # queue's t0: later windows of one flush parked later)
                    _m_window_age.observe(
                        time.monotonic() - batch[0].t_parked
                    )
                    _m_windows.inc()
                    # window span: tree-parented to the first parked
                    # packet; the rest of the fan-in is recorded as
                    # span_link records (many packet-ins -> one window)
                    wsp = batch[0].span.child(
                        "route_window", n_pairs=len(batch)
                    )
                    last_window_span = wsp.id or last_window_span
                    for p in batch:
                        p.park.end()
                        if p is not batch[0]:
                            wsp.link(p.span)
                    pairs = [(p.src, p.true_dst or p.dst) for p in batch]
                    dsp = wsp.child("dispatch")
                    t0 = time.perf_counter()
                    window = self._dispatch_window(pairs)
                    t_dispatched = time.perf_counter()
                    stage_wall += t_dispatched - t0
                    dsp.end(split_phase=window is not None)
                    if window is None:
                        # no split-phase provider on this bus (or
                        # pipelining off): serial resolve-then-install
                        if prev is not None:
                            _reap_timed(*prev)
                            prev = None
                        reply = self.bus.request(
                            ev.FindRoutesBatchRequest(pairs)
                        )
                        from sdnmpi_tpu.oracle.batch import WindowRoutes

                        t0 = time.perf_counter()
                        self._finish_batch(
                            batch, WindowRoutes.from_fdbs(reply.fdbs), wsp
                        )
                        wsp.end()
                        stage_wall += time.perf_counter() - t0
                        continue
                    _m_inflight.inc()
                # window k+1 is now in flight: reap + install window k
                # while the device chews on k+1
                if prev is not None:
                    _reap_timed(*prev)
                prev = (
                    (batch, window, wsp, t_dispatched) if batch else None
                )
        finally:
            self._flushing = False
            e2e = time.perf_counter() - t_flush0
            # the flush's spans are all closed by now, so the ambient
            # CURRENT_SPAN is gone — attribute the e2e sample to the
            # burst's last window span explicitly (README's "explain
            # this p99 spike" walkthrough starts from this exemplar)
            _m_e2e_s.observe(e2e, exemplar=last_window_span)
            if e2e > 0:
                # live twin of bench config 10's overlap_gain: the
                # serial-equivalent wall (host stages + the in-flight
                # device intervals a serial pass would have waited out)
                # over the achieved end-to-end wall. ~1.0 = serial;
                # >1 = device compute overlapped host decode+install
                _m_overlap_gain.set((stage_wall + hidden_wall) / e2e)

    def _next_window(self) -> list[_PendingRoute]:
        """Compose the next coalescer window, priority-aware (ISSUE 11).

        The window is capped at ``Config.coalesce_max_batch`` —
        overflow stays parked and spills into the NEXT window of the
        same flush loop, in arrival order, never one oversized window
        (routes parked mid-flush by re-entering packet-outs join the
        spill the same way; pinned by tests/test_serving.py). Within
        the cap, latency-sensitive entries (plain unicast, MPI
        point-to-point) are taken BEFORE bulk collective-member
        lookups, so an alltoall storm's backlog cannot push a single-
        pair request to the back of the flush; a single-class queue
        degenerates to plain arrival-order slicing (the PR-10
        behavior, byte-identical)."""
        cap = max(1, self.config.coalesce_max_batch)
        pending = self._pending
        if len(pending) <= cap:
            batch = pending[:]
            pending.clear()
            return batch
        sel = [i for i, p in enumerate(pending) if not p.bulk][:cap]
        if len(sel) < cap:
            room = cap - len(sel)
            if self.config.coalesce_wfq_weights:
                bulk_idx = self._wfq_bulk(pending, room)
            else:
                bulk_idx = []
                for i, p in enumerate(pending):
                    if p.bulk:
                        bulk_idx.append(i)
                        if len(bulk_idx) == room:
                            break
            sel = sorted(sel + bulk_idx)
        taken = set(sel)
        batch = [pending[i] for i in sel]
        # ONE compaction pass (in place — flush/census/enqueue all hold
        # this list): per-index deletes would make each flush O(cap x
        # backlog) on exactly the storm backlog this queue exists for
        pending[:] = [p for i, p in enumerate(pending) if i not in taken]
        return batch

    def _wfq_bulk(self, pending, room: int) -> list[int]:
        """Weighted fair split of a window's bulk room across tenants
        (Config.coalesce_wfq_weights, ISSUE 13 satellite): the room is
        allocated to the bulk tenants PRESENT in the backlog
        proportionally to their weights (unlisted tenants weigh 1.0)
        by largest-remainder rounding — deterministic, ties to the
        lexicographically-first tenant — and each tenant's allocation
        is served in its own arrival order. A tenant with less backlog
        than its share donates the surplus to the others, so no slot
        is wasted; a single-tenant backlog degenerates to the plain
        arrival-order fill byte-identically (pinned by
        tests/test_serving.py)."""
        weights_cfg = self.config.coalesce_wfq_weights
        groups: dict[str, list[int]] = {}
        for i, p in enumerate(pending):
            if p.bulk:
                groups.setdefault(
                    self.admission.tenant_of(p.src), []
                ).append(i)
        if not groups:
            return []
        weights = {
            t: max(float(weights_cfg.get(t, 1.0)), 1e-9) for t in groups
        }
        total_w = sum(weights.values())
        alloc = {
            t: min(len(groups[t]), int(room * weights[t] / total_w))
            for t in groups
        }
        used = sum(alloc.values())
        while used < room:
            best = None
            for t in sorted(groups):
                if alloc[t] >= len(groups[t]):
                    continue
                deficit = room * weights[t] / total_w - alloc[t]
                if best is None or deficit > best[0] + 1e-12:
                    best = (deficit, t)
            if best is None:
                break  # every tenant's backlog exhausted
            alloc[best[1]] += 1
            used += 1
        return [i for t in groups for i in groups[t][: alloc[t]]]

    def _dispatch_window(self, pairs, policy: str = "shortest", dirty=None):
        """Dispatch one window through the split-phase oracle API, or
        None when the serial path must be used (pipelining disabled, or
        a bus without the dispatch provider — e.g. minimal test
        stacks). ``dirty`` is the delta-narrowed revalidation's dirtied
        dpid set: the oracle re-scores the pairs with it as a device
        mask tensor and the reaped window carries per-pair ``touched``
        verdicts (events.DispatchRoutesBatchRequest)."""
        if not self.config.pipelined_install:
            return None
        try:
            return self.bus.request(
                ev.DispatchRoutesBatchRequest(pairs, policy=policy,
                                              dirty=dirty)
            ).window
        except LookupError:
            return None

    def _finish_batch(
        self, batch: list[_PendingRoute], wr, wsp=NULL_SPAN
    ) -> None:
        """Install one reaped window and finish its parked packets:
        vectorized FlowMod materialization + batched install for the
        whole window, then per-packet packet-out / broadcast fallback
        (the per-packet leg is inherently scalar — one PacketOut each)."""

        t0 = time.perf_counter()
        isp = wsp.child("install")
        routable = self._install_window(
            [(p.src, p.dst, p.true_dst) for p in batch], wr, parent=isp
        )
        isp.end(n_routable=int(np.count_nonzero(routable)))
        _m_install_s.observe(time.perf_counter() - t0)
        _m_routed.inc(int(np.count_nonzero(routable)))
        _m_unroutable.inc(len(batch) - int(np.count_nonzero(routable)))
        slo = self.slo
        if slo is not None:
            # per-tenant park-to-install latency for targeted tenants
            # (control/slo.py): the window is installed, so this is the
            # latency the tenant's rank experienced end to end
            slo.observe_batch(batch, time.monotonic())
        for k, p in enumerate(batch):
            p.span.end(routable=bool(routable[k]))
            if routable[k]:
                n = int(wr.hop_len[k])
                hops = wr.hop_dpid[k, :n]
                pos = np.nonzero(hops == p.dpid)[0]
                if not pos.size:
                    continue  # ingress switch not on the path
                buffered = p.buffer_id != of.OFP_NO_BUFFER
                self.southbound.packet_out(p.dpid, of.PacketOut(
                    data=None if buffered else p.pkt,
                    actions=(
                        of.ActionOutput(int(wr.hop_port[k, pos[0]])),
                    ),
                    buffer_id=p.buffer_id,
                ))
            elif p.true_dst is None:
                # routeless unicast falls back to controlled broadcast;
                # routeless MPI flows drop, exactly like the direct
                # path (reference: router.py:186)
                self.bus.request(
                    ev.BroadcastRequest(p.pkt, p.dpid, p.in_port)
                )

    def _install_window(self, entries, wr, parent=NULL_SPAN):
        """Install a whole window's flows from its WindowRoutes arrays.

        ``entries`` is ``[(src, dst, true_dst), ...]`` row-aligned with
        ``wr``. The hop rows are flattened and masked with array ops
        (live-datapath filter via ``np.isin``, last-hop rewrite
        selection with one ``np.where``); FDB dedup/bookkeeping stays a
        dict pass (it IS the dedup store), but builds no message
        objects; the surviving rows are grouped by switch with one
        ``np.argsort`` and the whole window ships as ONE
        :class:`~sdnmpi_tpu.protocol.openflow.FlowModBatch` through
        ``southbound.flow_mods_window`` — a single batched wire encode
        whose per-switch byte spans the southbound flushes, instead of
        one FlowMod dataclass + ``struct.pack`` per hop. Southbounds
        with only the per-switch batch entry point get per-group
        bursts; ones with neither fall back to the scalar per-hop
        path. Returns the [F] bool routable mask."""

        ln = np.asarray(wr.hop_len)
        routable = ln > 0
        if not len(entries) or not routable.any():
            return routable
        if (
            not self.config.pipelined_install
            or not hasattr(self.southbound, "flow_mods_batch")
        ):
            # the genuine legacy leg — pipelined_install=False is the
            # differential escape hatch and must reach the scalar
            # per-hop FlowMod + per-message encode path, not just
            # serialize the resolution
            for k, (src, dst, true_dst) in enumerate(entries):
                if routable[k]:
                    self._add_flows_for_path(wr.fdb(k), src, dst, true_dst)
            return routable

        from sdnmpi_tpu.utils.mac import int_to_mac, mac_to_int, macs_to_ints

        f, l = wr.hop_dpid.shape
        mask = np.arange(l)[None, :] < ln[:, None]
        pair_idx, hop_idx = np.nonzero(mask)
        dpid = wr.hop_dpid[pair_idx, hop_idx]
        port = wr.hop_port[pair_idx, hop_idx]
        last = hop_idx == ln[pair_idx] - 1
        src_keys = macs_to_ints([e[0] for e in entries])
        dst_keys = macs_to_ints([e[1] for e in entries])
        rew_keys = np.array(
            [mac_to_int(e[2]) if e[2] else -1 for e in entries], np.int64
        )
        m_src = src_keys[pair_idx]
        m_dst = dst_keys[pair_idx]
        m_rew = np.where(last, rew_keys[pair_idx], -1)
        dps = np.fromiter(self.dps, np.int64, len(self.dps))
        dps.sort()
        live = np.isin(dpid, dps)

        # dedup + FDB bookkeeping: dict ops only, one pass over the
        # flat rows. Hops on dead datapaths are not recorded (recording
        # them would dedup-suppress the install once the switch returns
        # — same rule as _add_flows_for_path).
        keep = np.zeros(len(dpid), bool)
        for i in np.nonzero(live)[0]:
            d = int(dpid[i])
            src, dst, _ = entries[pair_idx[i]]
            if self.fdb.exists(d, src, dst):
                continue
            p = int(port[i])
            self.fdb.update(d, src, dst, p)
            rw = int(m_rew[i])
            self.recovery.desired.record(
                d, src, dst, p, int_to_mac(rw) if rw >= 0 else None
            )
            self.bus.publish(ev.EventFDBUpdate(d, src, dst, p))
            keep[i] = True
        if keep.any():
            kd = dpid[keep]
            order = np.argsort(kd, kind="stable")
            kd = kd[order]
            burst = of.FlowModBatch(
                src=m_src[keep][order],
                dst=m_dst[keep][order],
                out_port=port[keep][order],
                rewrite=m_rew[keep][order],
                priority=self.config.priority_default,
                idle_timeout=self.config.flow_idle_timeout,
                hard_timeout=self.config.flow_hard_timeout,
            )
            _m_flows_installed.inc(len(kd))
            ssp = parent.child(
                "southbound_send", n_rows=len(kd),
                n_switches=int(np.count_nonzero(np.diff(kd)) + 1),
            )
            verdict = self._send_window(kd, burst)
            if self.config.recovery_plane:
                # dropped spans enter the bounded retry queue; barrier
                # xids arm the pending-ack table (barrier_rtt_seconds /
                # anti-entropy on timeout)
                self.recovery.note_send(verdict)
            ssp.end()
        return routable

    def _install_collective(self, vmac: VirtualMac) -> None:
        """Pre-route the whole collective in one load-balanced batch.

        The first packet of a collective reveals its type; every rank pair
        the collective's algorithm will send is routed in a single oracle
        call (spread across equal-cost paths, seeded with measured link
        utilization) and installed before those packets exist — the rest
        of the collective never touches the controller. The reference
        decodes the collective type but only logs it (router.py:182).

        Two install engines behind one decision: small collectives take
        the reference-shaped per-pair path (string MACs, exact per-pair
        dedup, one FDB event per hop); collectives with >=
        ``Config.block_install_threshold`` pairs take the array-native
        block path (int MAC keys, shared path blocks, one event per
        collective) — see :meth:`_install_collective_blocks`."""
        from sdnmpi_tpu.collectives import collective_pairs

        rankdb = self.bus.request(ev.CurrentProcessAllocationRequest()).processes
        ranks = rankdb.ranks()
        n = len(ranks)
        if n < 2:
            return
        # Pattern generators work in index space 0..n-1; registered rank
        # ids need not be contiguous, so map through the sorted rank list.
        # Root inference from the kickoff packet: BCAST/SCATTER round 0 is
        # the root's own first send (src == root); GATHER is flat, so
        # every packet's dst is the root. Binomial REDUCE cannot be
        # inferred — its first round is n/2 parallel sends with different
        # destinations, so a wrong guess is (n-2)/n likely; REDUCE
        # therefore routes reactively instead of installing a mis-rooted
        # tree.
        if vmac.coll_type == CollectiveType.REDUCE:
            return
        root_rank = {
            CollectiveType.BCAST: vmac.src_rank,
            CollectiveType.SCATTER: vmac.src_rank,
            CollectiveType.GATHER: vmac.dst_rank,
        }.get(vmac.coll_type)
        kwargs = {}
        if root_rank is not None:
            if root_rank not in ranks:
                return
            kwargs["root"] = ranks.index(root_rank)
        try:
            rank_pairs = collective_pairs(vmac.coll_type, n, **kwargs)
        except ValueError:
            return  # pattern not applicable (e.g. non-power-of-two ranks)

        if len(rank_pairs) >= self.config.block_install_threshold:
            return self._install_collective_blocks(
                vmac.coll_type, ranks, root_rank, rank_pairs, rankdb
            )

        # ranks need not be contiguous 0..n-1; pattern indices map onto the
        # sorted registered ranks, and the vMACs carry the *actual* ids
        todo: list[tuple[str, str, str]] = []  # (src_mac, pair_vmac, true_dst)
        pairs: list[tuple[str, str]] = []
        installed = self.fdb.pairs()  # one scan, O(1) lookups in the loop
        for si, di in sorted({(int(s), int(d)) for s, d in rank_pairs}):
            if si == di:
                continue
            s_rank, d_rank = ranks[si], ranks[di]
            src_mac = rankdb.get_mac(s_rank)
            dst_mac = rankdb.get_mac(d_rank)
            if not src_mac or not dst_mac:
                continue
            pair_vmac = VirtualMac(vmac.coll_type, s_rank, d_rank).encode()
            if (src_mac, pair_vmac) in installed:
                continue
            todo.append((src_mac, pair_vmac, dst_mac))
            pairs.append((src_mac, dst_mac))
        if not pairs:
            return

        window = self._dispatch_window(
            pairs, policy=self.config.collective_policy
        )
        if window is not None:
            # split-phase + vectorized window install: the whole
            # collective's FlowMods materialize as struct arrays and
            # ship as per-switch batched bursts
            wr = window.reap()
            max_congestion = wr.max_congestion
            self._install_window(todo, wr)
        else:
            reply = self.bus.request(
                ev.FindRoutesBatchRequest(
                    pairs, policy=self.config.collective_policy
                )
            )
            max_congestion = reply.max_congestion
            for (src_mac, pair_vmac, dst_mac), fdb in zip(todo, reply.fdbs):
                if fdb:
                    self._add_flows_for_path(fdb, src_mac, pair_vmac, dst_mac)
        log.info(
            "proactive install: collective %s, %d flows, max link load %s",
            vmac.coll_type,
            len(pairs),
            max_congestion,
        )

    def _install_collective_blocks(
        self,
        coll_type: int,
        ranks: list[int],
        root_rank,
        rank_pairs,
        rankdb,
        policy: str | None = None,
    ) -> None:
        """Array-native proactive install: no per-pair Python objects.

        The pattern's [F, 2] index pairs are deduplicated, filtered, and
        routed through one ``FindCollectiveRoutesRequest``; MAC keys and
        vMACs are encoded in batch (int48 arrays); each ECMP sub-flow's
        shared transit path goes to the fabric as ONE ``FlowPathBlock``
        whose member arrays are views into the sorted pair arrays. The
        reference would have run 16.7M packet-in -> DFS -> per-hop
        FlowMod cycles for the same outcome (reference:
        sdnmpi/router.py:125-160, sdnmpi/util/topology_db.py:59-84)."""

        from sdnmpi_tpu import native

        signature = (coll_type, root_rank, tuple(ranks))
        if self.collectives.get_by_signature(signature) is not None:
            return  # whole collective already installed
        policy = policy or self.config.collective_policy

        ranks_arr = np.asarray(ranks, dtype=np.int64)
        macs = [rankdb.get_mac(r) for r in ranks]
        # zero key marks "no MAC registered"; pairs touching one are
        # dropped below and the placeholder never reaches a switch
        present = np.array([bool(m) for m in macs])
        macs_str = [m or "00:00:00:00:00:00" for m in macs]
        n = len(ranks)

        src_idx = np.asarray(rank_pairs[:, 0], dtype=np.int64)
        dst_idx = np.asarray(rank_pairs[:, 1], dtype=np.int64)
        keep = (src_idx != dst_idx) & present[src_idx] & present[dst_idx]
        # dedup repeated pattern pairs (ring rounds repeat each neighbor
        # pair 2(n-1) times) — membership mask over the dense n^2 key
        # space, no comparison sort (np.unique costs seconds at 16.7M)
        seen = np.zeros(n * n, dtype=bool)
        if keep.all():
            seen[src_idx * n + dst_idx] = True
        else:
            seen[src_idx[keep] * n + dst_idx[keep]] = True
        key = np.nonzero(seen)[0]
        if not len(key):
            return
        src_idx, dst_idx = np.divmod(key, n)
        src_idx = src_idx.astype(np.int32)
        dst_idx = dst_idx.astype(np.int32)

        # phase-scheduler leg (ISSUE 8): with Config.schedule_collectives
        # the request carries schedule= (0 = auto phase count) and the
        # reply's routes is a PhasedFlowProgram whose per-phase device
        # programs are already dispatched; everything below then runs
        # per phase in _install_collective_phased. Default off: the
        # request is bit-identical to the pre-scheduler controller.
        schedule = (
            int(self.config.schedule_phases)
            if self.config.schedule_collectives else None
        )
        routes = self.bus.request(
            ev.FindCollectiveRoutesRequest(
                macs_str, src_idx, dst_idx, policy=policy,
                schedule=schedule,
            )
        ).routes
        if schedule is not None:
            return self._install_collective_phased(
                coll_type, ranks, root_rank, policy, macs_str,
                src_idx, dst_idx, routes,
            )

        # member-key production + counting sort by sub-flow, one native
        # pass; MAC keys + vMAC part LUTs through the preamble shared
        # with the phased leg (_vmac_luts owns the ABI comment)
        mac_keys, vmac_src_lut, vmac_dst_lut = _vmac_luts(
            coll_type, ranks_arr, macs_str
        )
        bounds, m_src, m_vmac, m_rew, m_fport = native.scatter_members(
            routes.pair_sub, src_idx, dst_idx, mac_keys,
            vmac_src_lut, vmac_dst_lut, mac_keys, routes.endpoint_port,
            0, routes.n_subflows,
        )

        cookie = self.collectives.next_cookie()
        # switch-level flow entries = sum over routable sub-flows of
        # members x path length (what the reference would install as
        # individual FlowMods)
        members_per_sub = np.diff(bounds)
        n_flows = int((members_per_sub * routes.hop_len).sum())
        if n_flows == 0:
            return  # nothing routable: don't record an empty install
        self.southbound.flow_block_set(
            of.FlowBlockSet(
                hop_dpid=routes.hop_dpid,
                hop_port=routes.hop_port,
                hop_len=routes.hop_len,
                bounds=bounds,
                src=m_src,
                dst=m_vmac,
                final_port=m_fport,
                rewrite=m_rew,
                priority=self.config.priority_default,
                cookie=cookie,
            )
        )

        # the dirty-set index for delta-narrowed revalidation: which
        # switches this collective's routed blocks actually ride (pad
        # rows are -1; unroutable sub-flows contribute nothing). One
        # np.unique over the hop arrays at install time buys skipping
        # whole-collective re-routes for every disjoint link flap later.
        hop_dpid = np.asarray(routes.hop_dpid)
        touched = frozenset(
            int(d) for d in np.unique(hop_dpid[hop_dpid >= 0])
        )
        # the directed-link index for congestion attribution (ISSUE 7):
        # consecutive hop pairs of each routed block, vectorized over
        # the same arrays — a hot link resolves to the collectives whose
        # blocks actually traverse it, not to everything in the fabric
        a, b = hop_dpid[:, :-1], hop_dpid[:, 1:]
        ridden = (a >= 0) & (b >= 0)
        links = frozenset(
            zip(a[ridden].astype(int).tolist(), b[ridden].astype(int).tolist())
        )
        self.collectives.add(
            CollectiveInstall(
                cookie, coll_type, tuple(ranks), root_rank,
                policy, macs_str, src_idx, dst_idx,
                n_pairs=len(src_idx), n_flows=n_flows,
                max_congestion=routes.max_congestion,
                switches=touched,
                links=links,
            )
        )
        self.bus.publish(
            ev.EventCollectiveInstalled(
                cookie, coll_type, len(src_idx), n_flows,
                routes.max_congestion,
            )
        )
        log.info(
            "proactive block install: collective %s, %d pairs, %d sub-flow "
            "blocks, %d switch flows, max link load %s",
            coll_type, len(src_idx), routes.n_subflows, n_flows,
            routes.max_congestion,
        )

    def _install_collective_phased(
        self,
        coll_type: int,
        ranks: list[int],
        root_rank,
        policy: str,
        macs_str: list[str],
        src_idx: np.ndarray,
        dst_idx: np.ndarray,
        program,
    ) -> None:
        """Install a scheduled collective's phased flow program
        (ISSUE 8), phase by phase through the PR-3 window plane.

        Every phase's device program was dispatched back to back by the
        oracle before this method sees the program, so reaping phase k
        here overlaps phases k+1..K's device compute — phasing adds
        pipeline depth, not serial latency. Each phase's reaped
        :class:`CollectiveRoutes` materializes into member-level FlowMod
        rows with array ops (one ``np.repeat`` cascade over the member
        scatter — no per-pair Python in the hop math), ships as ONE
        batched window per phase, and registers its barrier xids with
        the recovery plane: the barrier acks ARE the phase boundary,
        draining asynchronously while the next phase reaps. Desired
        rows are recorded per switch with ``collective=True`` (the
        collective table owns their lifecycle, not the SwitchFDB), so a
        switch that crashes and redials MID-PROGRAM reconciles to
        exactly the phases installed so far. Per-phase rows and the
        per-phase directed-link index land in the
        :class:`CollectiveInstall` (teardown re-drives the rows;
        congestion attribution resolves a hot link to the phase riding
        it)."""
        from sdnmpi_tpu import native

        ranks_arr = np.asarray(ranks, dtype=np.int64)
        mac_keys, vmac_src_lut, vmac_dst_lut = _vmac_luts(
            coll_type, ranks_arr, macs_str
        )
        dps = np.fromiter(self.dps, np.int64, len(self.dps))
        dps.sort()
        dps_set = set(dps.tolist())

        cookie = self.collectives.next_cookie()
        total_flows = 0
        switches: set[int] = set()
        links_all: set[tuple[int, int]] = set()
        phase_links: dict[tuple[int, int], list[int]] = {}
        # per-phase rows are ONE [N, 3] (dpid, src key, dst key) int
        # array, not N string tuples: a flagship-scale program retains
        # millions of rows for the install's lifetime so teardown can
        # re-derive exact matches, and the key arrays cost ~10x less —
        # MAC strings re-materialize in one memoized pass (_mac_rows)
        # at teardown/rollback
        phase_rows: list[tuple[int, np.ndarray]] = []
        phase_cong: list[float] = []
        # the current phase's shipped rows: a phase that fails
        # mid-program (device reap error, raising send) must not orphan
        # rows already on the switches and in the desired store with no
        # CollectiveInstall recorded to ever tear them down — the
        # rollback set is phase_rows plus this array
        arr_rows = np.empty((0, 3), np.int64)
        mac_memo: dict[int, str] = {}

        sp = start_span(
            "collective_program", cookie=cookie,
            n_phases=program.n_phases, n_pairs=program.n_pairs,
        )
        try:
            for plan in program.phases:
                t0 = time.perf_counter()
                psp = sp.child(
                    "collective_phase", phase=plan.phase,
                    n_pairs=plan.n_pairs,
                )
                try:
                    routes = plan.reap()
                    bounds, m_src, m_vmac, m_rew, m_fport = (
                        native.scatter_members(
                            routes.pair_sub,
                            src_idx[plan.pair_idx], dst_idx[plan.pair_idx],
                            mac_keys, vmac_src_lut, vmac_dst_lut, mac_keys,
                            routes.endpoint_port, 0, routes.n_subflows,
                        )
                    )
                    hop_dpid = np.asarray(routes.hop_dpid)
                    hop_port = np.asarray(routes.hop_port)
                    hop_len = np.asarray(routes.hop_len)
                    # member -> flat (member, hop) rows, all array ops:
                    # every member of sub-flow s contributes hop_len[s]
                    # rows riding s's shared transit path, with the
                    # member's own final port / rewrite on the last hop
                    m_sub = np.repeat(
                        np.arange(routes.n_subflows), np.diff(bounds)
                    )
                    rep = hop_len[m_sub]  # [M] rows per member
                    n_phase_flows = int(rep.sum())
                    if n_phase_flows == 0:
                        continue  # no routable member in this phase
                    row_m = np.repeat(np.arange(len(m_sub)), rep)
                    starts = np.zeros(len(m_sub), np.int64)
                    np.cumsum(rep[:-1], out=starts[1:])
                    hop_pos = np.arange(len(row_m)) - starts[row_m]
                    sub_r = m_sub[row_m]
                    r_dpid = hop_dpid[sub_r, hop_pos]
                    last = hop_pos == hop_len[sub_r] - 1
                    r_port = np.where(
                        last, m_fport[row_m], hop_port[sub_r, hop_pos]
                    ).astype(np.int32)
                    r_src = m_src[row_m]
                    r_dst = m_vmac[row_m]
                    r_rew = np.where(last, m_rew[row_m], -1)

                    live = np.isin(r_dpid, dps)
                    scalar = (
                        not self.config.pipelined_install
                        or not hasattr(self.southbound, "flow_mods_batch")
                    )
                    # every figure downstream — the flows metric, the
                    # phase/program events, CollectiveInstall.n_flows —
                    # counts LIVE rows only, consistent with the rows
                    # that actually ship, enter the desired store, and
                    # land in phase_rows for teardown/reconcile
                    n_live = int(live.sum())
                    failed: set[int] = set()
                    arr_rows = np.stack(
                        [
                            r_dpid[live].astype(np.int64),
                            r_src[live].astype(np.int64),
                            r_dst[live].astype(np.int64),
                        ],
                        axis=1,
                    )
                    # one bulk pass over the live rows — C-level int
                    # conversion (tolist), memoized MAC strings, ONE
                    # desired-store transaction — instead of a Python
                    # record() call per (member, hop) row
                    l_dpid = arr_rows[:, 0].tolist()
                    l_src = [
                        _mac_of(k, mac_memo)
                        for k in arr_rows[:, 1].tolist()
                    ]
                    l_dst = [
                        _mac_of(k, mac_memo)
                        for k in arr_rows[:, 2].tolist()
                    ]
                    l_port = r_port[live].tolist()
                    l_rew = [
                        _mac_of(k, mac_memo) if k >= 0 else None
                        for k in r_rew[live].tolist()
                    ]
                    self.recovery.desired.record_many(
                        l_dpid, l_src, l_dst, l_port, l_rew,
                        collective=True,
                    )
                    if scalar:
                        # the pipelined_install=False differential
                        # escape hatch (and batchless southbounds): one
                        # scalar FlowMod per row, permanent —
                        # byte-identical to the batched leg's rows
                        for d, src, dst, port, rewrite in zip(
                            l_dpid, l_src, l_dst, l_port, l_rew
                        ):
                            actions: tuple = (of.ActionOutput(port),)
                            if rewrite:
                                actions = (
                                    of.ActionSetDlDst(rewrite),
                                ) + actions
                            sent = self.southbound.flow_mod(d, of.FlowMod(
                                match=of.Match(dl_src=src, dl_dst=dst),
                                actions=actions,
                                priority=self.config.priority_default,
                            ))
                            if sent is False:
                                failed.add(d)
                    if n_live:
                        _m_flows_installed.inc(n_live)
                        if scalar:
                            verdict = (
                                InstallVerdict(dropped=sorted(failed))
                                if failed else None
                            )
                        else:
                            kd = r_dpid[live]
                            order = np.argsort(kd, kind="stable")
                            # no cookie on the wire: phased teardown and
                            # reconcile re-drive by exact (src, dst)
                            # match rows (phase_rows / desired store),
                            # and a recovery re-drive could not carry a
                            # cookie — rows stay byte-identical across
                            # fresh install, re-drive, and escape-hatch
                            # legs by carrying none anywhere
                            burst = of.FlowModBatch(
                                src=r_src[live][order],
                                dst=r_dst[live][order],
                                out_port=r_port[live][order],
                                rewrite=r_rew[live][order],
                                priority=self.config.priority_default,
                            )
                            verdict = self._send_window(kd[order], burst)
                        if self.config.recovery_plane:
                            # the phase boundary: its barrier xids arm
                            # the pending-ack table and drain while the
                            # next phase reaps (dropped scalar rows
                            # enter the same bounded retry queue)
                            self.recovery.note_send(verdict)

                    # reval index: the FULL ridden set, including
                    # switches whose rows were dead at install time —
                    # a later flap/redial of such a switch is exactly
                    # the delta that must re-route (and heal) this
                    # program, so it updates even for a phase that
                    # shipped NOTHING (all dpids dead needs the healing
                    # index most)
                    ridden_sw = hop_dpid[hop_dpid >= 0]
                    switches.update(
                        int(d) for d in np.unique(ridden_sw)
                    )
                    if not n_live:
                        # nothing shipped (every routed dpid left
                        # self.dps): no rows, no attribution, no phase
                        # event — the same rule as a phase with no
                        # routable member above
                        continue
                    total_flows += n_live
                    a, b = hop_dpid[:, :-1], hop_dpid[:, 1:]
                    ridden = (a >= 0) & (b >= 0)
                    # attribution index: only links a LIVE switch
                    # transmits on — a dead switch's rows never
                    # shipped, so no phase traffic leaves it, and the
                    # congestion report must not resolve a hot link to
                    # a phase with zero flows on it
                    links_p = {
                        lk
                        for lk in zip(
                            a[ridden].astype(int).tolist(),
                            b[ridden].astype(int).tolist(),
                        )
                        if lk[0] in dps_set
                    }
                    links_all.update(links_p)
                    for link in links_p:
                        phase_links.setdefault(link, []).append(plan.phase)
                    phase_rows.append((plan.phase, arr_rows))
                    arr_rows = np.empty((0, 3), np.int64)
                    phase_cong.append(float(routes.max_congestion))
                    _m_sched_phases.inc()
                    _m_sched_phase_install_s.observe(
                        time.perf_counter() - t0
                    )
                    self.bus.publish(
                        ev.EventCollectivePhaseInstalled(
                            cookie, plan.phase, program.n_phases,
                            plan.n_pairs, n_live,
                            float(routes.max_congestion),
                        )
                    )
                finally:
                    psp.end()
        except BaseException:
            # roll the partial program back: tear down every row already
            # shipped (they leave the desired store inside) so the
            # failure leaves no permanent flows that reconcile would
            # re-drive forever. Later phases' still-in-flight device
            # programs are simply abandoned — nothing of theirs reached
            # a switch.
            rollback = [
                row
                for _, arr in phase_rows
                for row in _mac_rows(arr, mac_memo)
            ]
            rollback.extend(_mac_rows(arr_rows, mac_memo))
            rollback = self._program_owned_rows(rollback)
            if rollback:
                self._del_flows_window(rollback)
            raise
        finally:
            sp.end(n_flows=total_flows)
        if total_flows == 0:
            return  # nothing routable: don't record an empty install

        max_phase = max(phase_cong, default=0.0)
        _m_sched_programs.inc()
        _m_sched_completion.set(float(sum(phase_cong)))
        _m_sched_max_phase.set(max_phase)
        self.collectives.add(
            CollectiveInstall(
                cookie, coll_type, tuple(ranks), root_rank,
                policy, macs_str, src_idx, dst_idx,
                n_pairs=len(src_idx), n_flows=total_flows,
                max_congestion=max_phase,
                switches=frozenset(switches),
                links=frozenset(links_all),
                n_phases=program.n_phases,
                phase_links={
                    link: tuple(sorted(set(ps)))
                    for link, ps in phase_links.items()
                },
                phase_rows=phase_rows,
            )
        )
        self.bus.publish(
            ev.EventCollectiveInstalled(
                cookie, coll_type, len(src_idx), total_flows, max_phase,
            )
        )
        log.info(
            "phased block install: collective %s, %d pairs, %d phases, "
            "%d switch flows, completion %s (max phase %s)",
            coll_type, len(src_idx), len(phase_rows), total_flows,
            sum(phase_cong), max_phase,
        )

    def _program_owned_rows(self, rows) -> list:
        """Filter a phased teardown/rollback burst down to the rows the
        program actually OWNS in the desired store: a reactive flow
        byte-identical to a phase row stays FDB-owned under the store's
        first-writer-wins rule, and deleting it here would yank a live
        FDB flow out from under its bookkeeping. Rows already gone from
        the store still delete (switch-side cleanup)."""
        desired = self.recovery.desired.flows
        out = []
        for d, s, t in rows:
            spec = desired.get(d, {}).get((s, t))
            if spec is None or spec.collective:
                out.append((d, s, t))
        return out

    def _remove_collective(self, install: CollectiveInstall) -> None:
        if install.n_phases and install.phase_rows is not None:
            # scheduled installs went through the window plane, not the
            # block plane: no cookie-recorded block entries exist — tear
            # down by the exact per-phase rows (one batched OFPFC_DELETE
            # window; the rows leave the desired store inside)
            memo: dict[int, str] = {}
            self._del_flows_window(
                self._program_owned_rows(
                    row
                    for _, arr in install.phase_rows
                    for row in _mac_rows(arr, memo)
                )
            )
        else:
            self.southbound.flow_blocks_delete(install.cookie)
        self.collectives.remove(install.cookie)
        self.bus.publish(ev.EventCollectiveRemoved(install.cookie))

    # -- flow lifecycle (no reference equivalent; SURVEY §2/§5) -----------

    def _flow_removed(self, event: ev.EventFlowRemoved) -> None:
        """A switch expired one of our flows (idle/hard timeout): drop
        the bookkeeping so the dedup cannot suppress a reinstall, and
        mirror the removal northbound. The switch already deleted its
        entry, so no FlowMod goes south. This is the handler for the
        OFPFF_SEND_FLOW_REM reply the reference requests but never
        consumes (reference: sdnmpi/router.py:61; SURVEY §2 defect)."""
        src, dst = event.match.dl_src, event.match.dl_dst
        if src is None or dst is None:
            return  # not one of the Router's exact-match flows
        if not self.fdb.exists(event.dpid, src, dst):
            return
        log.info(
            "flow expired on %s: %s -> %s (reason %d, %d pkts)",
            event.dpid, src, dst, event.reason, event.packet_count,
        )
        self.fdb.remove(event.dpid, src, dst)
        # the switch expired it on purpose: it is no longer desired
        # either (a reconcile must not resurrect a timed-out flow)
        self.recovery.desired.remove(event.dpid, src, dst)
        self.bus.publish(ev.EventFDBRemove(event.dpid, src, dst))

    def _publish_fdb_removes(self, rows: list[tuple[int, str, str]]) -> None:
        """Mirror a teardown northbound: ONE
        :class:`~sdnmpi_tpu.control.events.EventFDBRemoveBatch` for a
        burst (a revalidation pass or rank exit tears down hundreds of
        rows — per-row events cost one RPC broadcast each), the
        pre-batch per-row :class:`EventFDBRemove` for a single removal.
        Per-row-only consumers attach via ``ev.subscribe_fdb_removes``
        (the compat shim expanding batches)."""
        if not rows:
            return
        if len(rows) == 1:
            self.bus.publish(ev.EventFDBRemove(*rows[0]))
        else:
            self.bus.publish(ev.EventFDBRemoveBatch(list(rows)))

    def _datapath_down(self, event: ev.EventDatapathDown) -> None:
        self.dps.discard(event.dpid)
        self._publish_fdb_removes([
            (event.dpid, src, dst)
            for (src, dst) in self.fdb.fdb.get(event.dpid, {})
        ])
        self.fdb.remove_switch(event.dpid)
        # pending barriers/retries are moot; the DESIRED set survives —
        # it is exactly what the reconciler re-drives on redial
        self.recovery.forget(event.dpid)

    # -- failure-domain recovery (ISSUE 5; no reference equivalent) --------

    def _datapath_up(self, event: ev.EventDatapathUp) -> None:
        self.dps.add(event.dpid)
        if not self.config.recovery_plane:
            return
        cap = self.config.reconcile_max_per_flush
        if cap > 0 and self._reconcile_spent >= cap:
            # mass-redial storm shaping (ISSUE 15 satellite): this
            # flush window's reconcile budget is spent — park the
            # reconcile; the anti-entropy tick drains the queue at the
            # same cap. The switch serves from its (possibly stale or
            # empty) table meanwhile; reconcile order is arrival order.
            self.recovery.note_reconcile_deferred()
            if event.dpid not in self._reconcile_pending:
                self._reconcile_pending.append(event.dpid)
            return
        self._reconcile_spent += 1
        if event.dpid in self._reconcile_pending:
            # a parked switch bounced and redialed with budget free:
            # this reconcile covers it — don't re-drive from the queue
            self._reconcile_pending.remove(event.dpid)
        self._reconcile_datapath(event.dpid)

    def _reconcile_datapath(self, dpid: int) -> None:
        """Re-drive a returning datapath's entire desired flow set.

        A switch that crashed and redialed comes back with an EMPTY
        flow table; one that merely lost its TCP session kept its flows
        (re-driving is then idempotent — OF 1.0 ADD replaces an
        identical match+priority entry). Either way the switch ends up
        byte-identical to a fresh install of the desired set, through
        the same batched ``flow_mods_window`` path, without waiting for
        packet-ins to fault the flows back in one at a time. Teardowns
        that were unconfirmed when the switch went away re-drive too
        (the lost-delete ledger): the bounced-switch case where stale
        flows survived in the kept table."""
        rows = self.recovery.desired.entries_for(dpid)
        self.recovery.forget(dpid)  # a redial obsoletes prior bookkeeping
        # forget() parked any unconfirmed teardowns; rows re-desired
        # since are covered by the reinstall (ADD replaces the entry)
        lost = [
            (s, d) for (s, d) in sorted(self.recovery.take_lost_deletes(dpid))
            if not self.recovery.desired.has(dpid, s, d)
        ]
        if (not rows and not lost) or dpid not in self.dps:
            return
        log.info(
            "reconciling datapath %#x: re-driving %d desired flows, "
            "%d lost teardowns", dpid, len(rows), len(lost),
        )
        sp = start_span(
            "reconcile", dpid=dpid, n_flows=len(rows), n_lost=len(lost)
        )
        try:
            if lost:
                verdict = self._send_deletes(dpid, lost)
                self.recovery.note_send(
                    verdict, delete_rows={dpid: set(lost)}
                )
            if not rows:
                return
            # the down-edge cleared this switch's FDB rows; restore the
            # bookkeeping the installs below re-create on the switch.
            # Rows installed by the phase scheduler's window plane
            # (spec.collective) re-drive like any other desired row but
            # carry NO SwitchFDB bookkeeping — the collective table
            # owns their lifecycle (ISSUE 8).
            for src, dst, spec in rows:
                if spec.collective:
                    continue
                if not self.fdb.exists(dpid, src, dst):
                    self.fdb.update(dpid, src, dst, spec.out_port)
                    self.bus.publish(
                        ev.EventFDBUpdate(dpid, src, dst, spec.out_port)
                    )
            self.recovery.note_reconcile(len(rows))
            verdict = self._send_desired(dpid, rows)
            self.recovery.note_send(verdict)
        finally:
            sp.end()

    def _send_deletes(self, dpid: int, rows) -> "InstallVerdict | None":
        """Tear down ``rows`` (``[(src, dst), ...]``) on one switch —
        the retry/reconcile twin of :meth:`_send_desired`, honoring the
        same ``pipelined_install`` escape hatch and batchless-southbound
        fallback as every other send site."""
        if (
            not self.config.pipelined_install
            or not hasattr(self.southbound, "flow_mods_batch")
        ):
            ok = True
            for src, dst in rows:
                if self._del_flow(dpid, src, dst) is False:
                    ok = False
            return InstallVerdict(
                sent=[dpid] if ok else [], dropped=[] if ok else [dpid]
            )

        from sdnmpi_tpu.utils.mac import macs_to_ints

        return self._send_window(
            np.full(len(rows), dpid, np.int64),
            of.FlowModBatch(
                src=macs_to_ints([r[0] for r in rows]),
                dst=macs_to_ints([r[1] for r in rows]),
                out_port=np.zeros(len(rows), np.int32),
                rewrite=None,
                priority=self.config.priority_default,
                command=of.OFPFC_DELETE,
            ),
        )

    def _send_desired(self, dpid: int, rows) -> "InstallVerdict | None":
        """Install ``rows`` (``[(src, dst, FlowSpec), ...]``) on one
        switch through the batched window path; scalar fallback for the
        ``pipelined_install=False`` escape hatch and batchless
        southbounds."""
        if (
            not self.config.pipelined_install
            or not hasattr(self.southbound, "flow_mods_batch")
        ):
            ok = True
            for src, dst, spec in rows:
                actions = (
                    (of.ActionSetDlDst(spec.rewrite),) if spec.rewrite else ()
                )
                if spec.collective:
                    # phase-scheduler rows re-drive PERMANENT (their
                    # fresh install carries no timeouts), same as the
                    # batched leg's collective split below
                    sent = self.southbound.flow_mod(dpid, of.FlowMod(
                        match=of.Match(dl_src=src, dl_dst=dst),
                        actions=actions + (of.ActionOutput(spec.out_port),),
                        priority=self.config.priority_default,
                    ))
                else:
                    sent = self._add_flow(
                        dpid, src, dst, spec.out_port, actions
                    )
                ok = ok and sent is not False
            return InstallVerdict(
                sent=[dpid] if ok else [], dropped=[] if ok else [dpid]
            )

        from sdnmpi_tpu.utils.mac import mac_to_int, macs_to_ints

        # collective rows (the phase scheduler's window plane) installed
        # permanent — splitting the burst keeps the re-drive
        # byte-identical to each row's fresh install when the config
        # carries flow timeouts
        verdict: InstallVerdict | None = None
        for collective in (False, True):
            part = [r for r in rows if r[2].collective is collective]
            if not part:
                continue
            burst = of.FlowModBatch(
                src=macs_to_ints([r[0] for r in part]),
                dst=macs_to_ints([r[1] for r in part]),
                out_port=np.array([r[2].out_port for r in part], np.int32),
                rewrite=np.array(
                    [mac_to_int(r[2].rewrite) if r[2].rewrite else -1
                     for r in part],
                    np.int64,
                ),
                priority=self.config.priority_default,
                idle_timeout=(
                    0 if collective else self.config.flow_idle_timeout
                ),
                hard_timeout=(
                    0 if collective else self.config.flow_hard_timeout
                ),
            )
            _m_flows_installed.inc(len(part))
            v = self._send_window(np.full(len(part), dpid, np.int64), burst)
            if isinstance(v, InstallVerdict):
                if verdict is None:
                    verdict = InstallVerdict()
                verdict.sent += v.sent
                verdict.dropped += v.dropped
                verdict.barriers += v.barriers
            elif verdict is None:
                verdict = v
        if isinstance(verdict, InstallVerdict):
            # restore the InstallVerdict contract across the split: the
            # dpid appears in exactly ONE of sent/dropped, once — both
            # parts failing must not list it twice (note_send would
            # burn two retry attempts per actual failure), and a
            # half-failed split needs the retry (dropped wins)
            dropped = set(verdict.dropped)
            verdict.dropped = sorted(dropped)
            verdict.sent = sorted(set(verdict.sent) - dropped)
        return verdict

    # -- audit-plane heal seams (ISSUE 15; control/audit.py) ---------------

    def audit_redrive(self, dpid: int, rows) -> None:
        """Targeted repair of confirmed missing / counter-dead rows:
        re-drive EXACTLY these desired rows (``[(src, dst, FlowSpec),
        ...]``) through the reconcile install path — OF 1.0 ADD
        replaces a corrupt entry in place, so one bad row costs one
        row's re-install, never a wipe. Verdicts feed the same
        recovery bookkeeping as any install."""
        if dpid not in self.dps or not rows:
            return
        sp = start_span("audit_redrive", dpid=dpid, n_rows=len(rows))
        try:
            self.recovery.note_reconcile(len(rows))
            verdict = self._send_desired(dpid, rows)
            if self.config.recovery_plane:
                self.recovery.note_send(verdict)
        finally:
            sp.end()

    def audit_delete(self, dpid: int, rows) -> None:
        """Targeted teardown of confirmed orphan rows (``[(src, dst),
        ...]`` — rows the fabric holds that no desired state ever
        recorded). A dropped teardown re-drives as a teardown through
        the recovery plane's delete-carrying retry."""
        if dpid not in self.dps or not rows:
            return
        sp = start_span("audit_delete", dpid=dpid, n_rows=len(rows))
        try:
            verdict = self._send_deletes(dpid, rows)
            if self.config.recovery_plane:
                self.recovery.note_send(
                    verdict, delete_rows={dpid: set(rows)}
                )
        finally:
            sp.end()

    def recovery_tick(self, now: float | None = None) -> None:
        """One anti-entropy pass (per EventStatsFlush — the Monitor's
        cadence, the same edge the utilization plane flushes on): expire
        un-acked barriers into resync retries, then re-drive every due
        retry. Bounded per switch by ``Config.install_retry_max``;
        exhaustion escalates to a full resync (:meth:`_resync_datapath`)
        instead of silent desired/installed divergence."""
        if not self.config.recovery_plane:
            return
        now = time.monotonic() if now is None else now
        # a fresh flush window: the reconcile budget renews and the
        # deferred-reconcile queue drains under the same cap, oldest
        # first (rate-shaped mass-redial recovery, ISSUE 15 satellite)
        self._reconcile_spent = 0
        cap = self.config.reconcile_max_per_flush
        while self._reconcile_pending and (
            cap <= 0 or self._reconcile_spent < cap
        ):
            dpid = self._reconcile_pending.pop(0)
            if dpid not in self.dps:
                continue  # went away again; reconcile-on-up will re-queue
            self._reconcile_spent += 1
            self._reconcile_datapath(dpid)
        if self._resync_due:
            # jitter-deferred wipe-resync republishes (ISSUE 20
            # satellite): the EventDatapathUp re-drive lands through the
            # same budgeted reconcile path above, staggered by the
            # seeded draw taken at escalation time
            ready = [x for x in self._resync_due if x[0] <= now]
            if ready:
                self._resync_due = [x for x in self._resync_due if x[0] > now]
                for _t, dpid in sorted(ready):
                    if dpid not in self.dps:
                        continue
                    self.bus.publish(ev.EventDatapathUp(dpid))
                    if self.audit is not None:
                        self.audit.request_verify(dpid)
        for dpid, (rows, resync) in self.recovery.expire_barriers(
            now, self.config.barrier_timeout_s
        ).items():
            # the window may or may not have applied — only a re-drive
            # (of the delete rows for a teardown window, of the desired
            # set otherwise) makes the switch's state known again
            if not self.recovery.schedule(
                dpid, now, deletes=rows, resync=resync
            ):
                self._resync_datapath(dpid, now)
        for dpid, retry in self.recovery.pop_due(now):
            if dpid not in self.dps:
                # reconcile-on-up owns dead datapaths; unconfirmed
                # teardowns park in the lost-delete ledger so a bounced
                # switch that KEPT its table still sheds them
                self.recovery.stash_lost_deletes(dpid, retry.deletes)
                continue
            self.recovery.note_retry()
            # the retry re-drive is a root span of its own (no request
            # tree to hang from): flight-recorder bundles show WHICH
            # switch was being re-driven when an anomaly froze
            sp = start_span(
                "recovery_retry", dpid=dpid, resync=retry.resync,
                n_deletes=len(retry.deletes),
            )
            t0 = time.perf_counter()
            ok = True
            deletes = [
                (s, d) for (s, d) in sorted(retry.deletes)
                # a pair re-installed since its dropped teardown is
                # covered by the reinstall (ADD replaced the entry);
                # deleting it now would wipe the fresh flow
                if not self.recovery.desired.has(dpid, s, d)
            ]
            try:
                if deletes:
                    verdict = self._send_deletes(dpid, deletes)
                    if verdict is not None:
                        self.recovery.note_send(
                            verdict, delete_rows={dpid: set(deletes)},
                            reschedule=False,
                        )
                        ok = ok and dpid not in verdict.dropped
                if retry.resync:
                    rows = self.recovery.desired.entries_for(dpid)
                    if rows:
                        self.recovery.note_reconcile(len(rows))
                        verdict = self._send_desired(dpid, rows)
                        if verdict is not None:
                            self.recovery.note_send(
                                verdict, reschedule=False
                            )
                            ok = ok and dpid not in verdict.dropped
                if ok:
                    self.recovery.succeed(dpid)
                elif not self.recovery.schedule(
                    now=now, dpid=dpid, deletes=set(deletes),
                    resync=retry.resync,
                ):
                    self._resync_datapath(dpid, now)
            finally:
                sp.end(ok=ok)
                _m_recovery_redrive_s.observe(time.perf_counter() - t0)

    def _resync_datapath(self, dpid: int, now: float | None = None) -> None:
        """Last-resort escalation after retry exhaustion: wipe the
        switch's flow table with an all-wildcard OFPFC_DELETE (the OF
        1.0 "forget everything" idiom) and republish EventDatapathUp so
        EVERY app re-drives its per-switch state — the TopologyManager
        its bootstrap flows, the ProcessManager its announcement trap,
        this Router the desired set — exactly as on a redial. The
        switch's state is then known-good again regardless of which
        windows it lost.

        The republish is staggered by one seeded jitter draw over the
        retry backoff base (ISSUE 20 satellite: a fabric-wide
        exhaustion storm — or a pair failover — must not re-drive
        every switch in lockstep); with a zero backoff base (the
        synchronous-test posture) it stays immediate."""
        if dpid not in self.dps:
            return
        self.recovery.note_resync()
        log.warning(
            "datapath %#x: retries exhausted; wiping and resyncing", dpid
        )
        # the escalation span: the chaos-soak acceptance asserts a
        # frozen bundle's span trees contain this stage (ISSUE 7)
        sp = start_span("recovery_resync", dpid=dpid)
        try:
            self.southbound.flow_mod(dpid, of.FlowMod(
                match=of.Match(), actions=(), priority=0,
                command=of.OFPFC_DELETE,
            ))
            delay = self.recovery.jitter(self.config.install_retry_backoff_s)
            if delay > 0.0:
                now = time.monotonic() if now is None else now
                self._resync_due.append((now + delay, dpid))
                return  # recovery_tick republishes (+ verify) when due
            self.bus.publish(ev.EventDatapathUp(dpid))
        finally:
            sp.end()
        if self.audit is not None:
            # the escalation no longer trusts the wipe: the audit plane
            # verifies this switch ahead of its round-robin turn on the
            # next sweep (ISSUE 15 — the flow-stats-based table
            # verification carried as an open item since PR 5)
            self.audit.request_verify(dpid)

    def _effective_dst(self, dst: str) -> str | None:
        """The MAC a flow actually targets: for MPI flows the dst is a
        virtual MAC and the real target is the rank's current host."""
        if not is_sdn_mpi_addr(dst):
            return dst
        try:
            vmac = VirtualMac.decode(dst)
        except ValueError:
            return dst
        return self.bus.request(ev.RankResolutionRequest(vmac.dst_rank)).mac

    def _reval_dirty_set(self):
        """Narrow the next revalidation pass through the epoch gate.

        Returns:
        - an empty set: nothing advanced since the last pass (repeat
          EventTopologyChanged with no TopologyDB version bump and no
          UtilPlane epoch publish) — skip the pass entirely;
        - a non-empty set of dpids: the delta log covers the gap with
          pure link *deletes*, so only flows whose installed paths
          touch one of these switches re-route. Delete narrowing is
          SOUND, not just safe: a pair's chosen shortest path changes
          under a delete only if it rode the deleted link, so its
          installed hops contain both endpoints and the pair is always
          narrowed in — narrowed and full passes leave bit-identical
          FDB/desired state (the ISSUE-6 differential fence,
          tests/test_delta_reval.py);
        - None: no basis to narrow (first pass, broken/overflowed
          delta log, host/switch membership deltas, the utilization
          plane moved under an unchanged graph, ``Config.delta_reval``
          off, or the gap contains a non-narrowable link ADD) — full
          pass. Adds fall back deliberately: a restored cable can
          shorten flows whose CURRENT detour avoids both of its
          endpoints entirely (a torus neighbor pair's around-the-ring
          detour), so endpoint narrowing would strand stale routes and
          break the narrowed-vs-full bit-identity the escape hatch
          guarantees. The ONE exception (ISSUE 13): an add interior to
          a single pod of a generator-certified PodMap narrows to that
          pod's member set — the proof lives with
          ``narrowed_dirty_set`` in core/topology_db.py.

        Precedence note: when the graph changed AND the utilization
        plane also moved, the link-delta narrowing still applies — the
        utilization epoch participates only in the skip/no-skip
        decision for an unchanged graph. Utilization-driven
        re-spreading of flows untouched by the link deltas is deferred
        (they would not have re-routed at all without a topology event,
        so this matches the pre-gate steady state); a pass that cannot
        be narrowed re-spreads everything as before.
        """
        try:
            db = self.bus.request(ev.CurrentTopologyRequest()).topology
        except LookupError:
            return None  # minimal stacks without a TopologyManager
        try:
            util_epoch = self.bus.request(ev.UtilEpochRequest()).epoch
        except LookupError:
            util_epoch = -1
        version = getattr(db, "version", None)
        if version is None:
            return None  # duck-typed stand-in without the epoch counter
        last_v, last_u = self._reval_version, self._reval_util_epoch
        self._reval_version = version
        self._reval_util_epoch = util_epoch
        if last_v is None:
            return None  # first pass: no baseline
        if version == last_v:
            # duplicate topology signal; skip unless utilization moved
            return set() if util_epoch == last_u else None
        if not self.config.delta_reval:
            return None  # escape hatch: always the full pass
        deltas_since = getattr(db, "deltas_since", None)
        deltas = deltas_since(last_v) if deltas_since else None
        if deltas is None:
            return None  # log broken (structural) or overflowed
        # ONE copy of the delta-narrowing kind rules, shared with the
        # route cache's invalidation sweep (the proofs live there).
        # The PodMap pair additionally narrows certified intra-pod
        # link ADDS to the pod's member set (ISSUE 13): an affected
        # flow necessarily has an endpoint inside the pod, and its
        # installed path rides that endpoint switch — always narrowed
        # in, so narrowed == full stays bit-identical.
        from sdnmpi_tpu.core.topology_db import narrowed_dirty_set

        return narrowed_dirty_set(
            deltas, getattr(db, "podmap", None),
            db if hasattr(db, "live_border_set") else None,
        )

    def _revalidate_flows(self) -> None:
        """Recompute installed routes after a topology change; tear down
        hops that no longer lie on the chosen path and eagerly reinstall
        the surviving routes — the control-plane leg of the incremental
        churn dataflow (ISSUE 6).

        Epoch-gated and delta-narrowed end to end: a pass with neither
        the TopologyDB version nor the UtilPlane epoch advanced is a
        no-op; when the PR-1 delta log covers the gap with pure link
        deltas (and ``Config.delta_reval``), only the flows whose
        installed paths touch a dirtied switch re-route, and
        block-installed collectives re-route only when the dirtied set
        intersects the switches their installed blocks actually ride.
        Surviving flows re-score through the oracle's delta entry point
        in PIPELINED dispatch/reap windows (window k+1's device compute
        overlaps window k's diff + install), per-pair hop diffs tear
        down and reinstall only the *changed spans*, and both the
        teardown and the reinstall ship as batched windows
        (``_del_flows_window`` / ``_install_window``) instead of scalar
        per-hop FlowMods. A cable flap costs O(affected flows), never a
        re-route of the fabric."""
        dirty = self._reval_dirty_set()
        if dirty is not None and not dirty:
            _m_revalidations_skipped.inc()
            return  # nothing advanced since the last pass
        _m_revalidations.inc()
        # one span tree per revalidation pass (ISSUE 7): root `reval`
        # with per-chunk reval_rescore/reval_diff/reval_install stages —
        # emitted identically by the pipelined path, the serial
        # (pipelined_install=False) fallback, and the link-add full
        # pass, so traces stay comparable across escape hatches
        rsp = start_span(
            "reval",
            narrowed=dirty is not None,
            n_dirty=0 if dirty is None else len(dirty),
        )
        try:
            self._revalidate_flows_spanned(dirty, rsp)
        finally:
            rsp.end()

    def _revalidate_flows_spanned(self, dirty, rsp) -> None:
        for install in self.collectives:
            if (
                dirty is not None
                and install.switches
                and dirty.isdisjoint(install.switches)
            ):
                continue  # none of its installed blocks ride a dirty switch
            self._remove_collective(install)
            self._reinstall_collective(install)

        flows: dict[tuple[str, str], dict[int, int]] = {}
        for dpid, src, dst, port in self.fdb.entries():
            flows.setdefault((src, dst), {})[dpid] = port
        if dirty is not None:
            flows = {
                pair: hops for pair, hops in flows.items()
                if not dirty.isdisjoint(hops)
            }
        if not flows:
            return

        doomed: list[tuple[int, str, str]] = []  # batched teardown burst
        resolved: list[tuple[tuple[str, str], str]] = []
        for src, dst in flows:
            effective = self._effective_dst(dst)
            if effective is None:
                # the rank behind this vMAC is gone: tear it all down
                for dpid, _ in flows[(src, dst)].items():
                    self.fdb.remove(dpid, src, dst)
                    doomed.append((dpid, src, dst))
                continue
            resolved.append(((src, dst), effective))
        self._publish_fdb_removes(doomed)
        self._del_flows_window(doomed)

        from sdnmpi_tpu.oracle.batch import WindowRoutes

        _m_reval_affected.observe(len(resolved))

        def process(chunk, wr, csp=NULL_SPAN) -> None:
            """Diff + re-drive one reaped window: per-pair hop diffs
            pick the changed spans; the span teardown flushes as ONE
            batched OFPFC_DELETE window BEFORE the reinstall window (a
            rerouted pair's new flow shares the old one's (src, dst)
            match, so a delete landing after the install would wipe the
            fresh entry too), and the reinstall ships through the same
            vectorized window installer the packet-in path uses — the
            FDB dedup inside it keeps surviving hops untouched, so only
            changed spans reach the wire. ``csp`` is the chunk's span;
            the diff and install stages record as its children plus the
            reval_diff/install_seconds histograms."""
            chunk_doomed: list[tuple[int, str, str]] = []
            entries: list[tuple[str, str, str | None]] = []
            try:
                t0 = time.perf_counter()
                dsp = csp.child("reval_diff", n_pairs=len(chunk))
                try:
                    for k, ((src, dst), effective) in enumerate(chunk):
                        installed = flows[(src, dst)]
                        n = int(wr.hop_len[k])
                        new_hops = {
                            int(wr.hop_dpid[k, h]): int(wr.hop_port[k, h])
                            for h in range(n)
                        }
                        for dpid, port in installed.items():
                            if new_hops.get(dpid) != port:
                                self.fdb.remove(dpid, src, dst)
                                chunk_doomed.append((dpid, src, dst))
                        entries.append((
                            src, dst,
                            effective if is_sdn_mpi_addr(dst) else None,
                        ))
                finally:
                    dsp.end(n_changed=len(chunk_doomed))
                    _m_reval_diff_s.observe(time.perf_counter() - t0)
                t0 = time.perf_counter()
                isp = csp.child(
                    "reval_install", n_changed=len(chunk_doomed)
                )
                try:
                    self._publish_fdb_removes(chunk_doomed)
                    self._del_flows_window(chunk_doomed)
                    self._install_window(entries, wr, parent=isp)
                finally:
                    isp.end()
                    _m_reval_install_s.observe(time.perf_counter() - t0)
            finally:
                # a raising stage must not leak the chunk span open —
                # the anomaly bundle frozen FOR that failure needs the
                # (partial) revalidation tree completed, not buffered
                csp.end()
            if wr.touched is not None:
                # device-computed attribution: flows whose new path left
                # the dirty region entirely (they drained off the flap)
                _m_reval_drained.inc(
                    int(np.count_nonzero(~wr.touched & (wr.hop_len > 0)))
                )

        def reap_prev(prev) -> None:
            chunk, window, csp, resc, t_re = prev
            try:
                wr = window.reap()
            except BaseException:
                # raising reap: close the rescore + chunk spans (same
                # hardening the flush loop's PR-4 round-2 fix applied)
                resc.end()
                _m_reval_rescore_s.observe(time.perf_counter() - t_re)
                csp.end()
                raise
            resc.end()
            _m_reval_rescore_s.observe(time.perf_counter() - t_re)
            process(chunk, wr, csp)

        # pipelined re-scoring: windows of coalesce_max_batch pairs
        # double-buffer through the delta dispatch API — window k+1
        # computes on device while window k diffs and installs
        step = max(1, self.config.coalesce_max_batch)
        prev: tuple | None = None  # (chunk, window, csp, rescore span, t0)
        for lo in range(0, len(resolved) + 1, step):
            chunk = resolved[lo : lo + step]
            window = None
            csp = resc = NULL_SPAN
            t_re = 0.0
            if chunk:
                pairs = [(src, eff) for (src, _), eff in chunk]
                csp = rsp.child("reval_window", n_pairs=len(chunk))
                resc = csp.child("reval_rescore")
                t_re = time.perf_counter()
                window = self._dispatch_window(pairs, dirty=dirty)
                if window is None:
                    # serial fallback (pipelining off / minimal stacks):
                    # blocking batch request, same stage spans and
                    # histograms as the pipelined leg
                    if prev is not None:
                        reap_prev(prev)
                        prev = None
                    reply = self.bus.request(
                        ev.FindRoutesBatchRequest(pairs)
                    )
                    resc.end()
                    _m_reval_rescore_s.observe(time.perf_counter() - t_re)
                    process(chunk, WindowRoutes.from_fdbs(reply.fdbs), csp)
                    continue
            if prev is not None:
                reap_prev(prev)
            prev = (chunk, window, csp, resc, t_re) if chunk else None
        if prev is not None:  # last partial chunk (len % step != 0):
            # the trailing empty range slot that would have flushed it
            # only exists when len(resolved) is a step multiple
            reap_prev(prev)

    def _reinstall_collective(self, install: CollectiveInstall) -> None:
        """Re-route a previously installed collective against the current
        topology/process state (used by revalidation and restore). The
        rankdb is re-consulted so moved ranks get their new MACs, and
        ranks that exited since the install are dropped — only the LIVE
        rank subset is reinstalled (pattern pairs touching a dead rank
        are filtered and the survivors remapped onto the live rank
        list), so the new install's record and signature describe what
        is actually on the switches instead of leaking dead ranks."""
        rankdb = self.bus.request(ev.CurrentProcessAllocationRequest()).processes
        alive = np.array(
            [bool(rankdb.get_mac(r)) for r in install.ranks], bool
        )
        if int(alive.sum()) < 2:
            return
        src_idx = np.asarray(install.src_idx)
        dst_idx = np.asarray(install.dst_idx)
        ranks = list(install.ranks)
        if not alive.all():
            keep = alive[src_idx] & alive[dst_idx]
            if not keep.any():
                return
            remap = (np.cumsum(alive) - 1).astype(np.int32)
            src_idx = remap[src_idx[keep]]
            dst_idx = remap[dst_idx[keep]]
            ranks = [r for r, a in zip(ranks, alive) if a]
        self._install_collective_blocks(
            install.coll_type,
            ranks,
            install.root if install.root in ranks else None,
            np.stack([src_idx, dst_idx], axis=1),
            rankdb,
            policy=install.policy,
        )

    def _process_delete(self, event: ev.EventProcessDelete) -> None:
        """Tear down flows addressed to the exited rank's virtual MAC."""
        for install in self.collectives.with_rank(event.rank):
            self._remove_collective(install)
        doomed = []
        for dpid, src, dst, _ in list(self.fdb.entries()):
            if not is_sdn_mpi_addr(dst):
                continue
            try:
                vmac = VirtualMac.decode(dst)
            except ValueError:
                continue
            if vmac.dst_rank == event.rank:
                doomed.append((dpid, src, dst))
        for dpid, src, dst in doomed:
            self.fdb.remove(dpid, src, dst)
        # one EventFDBRemoveBatch + one batched OFPFC_DELETE window for
        # the whole rank exit (the RPC mirror gets one message, not one
        # per torn-down row)
        self._publish_fdb_removes(doomed)
        self._del_flows_window(doomed)

    def reinstall_pairs(self, pairs: list[tuple[str, str]]) -> None:
        """Re-route and install flows for (src, dst) match pairs — used by
        checkpoint restore, where only the pair set is trusted: paths are
        recomputed against the current topology and pushed to the live
        switches, so bookkeeping and switch state stay coherent."""
        resolved: list[tuple[str, str, str]] = []
        for src, dst in pairs:
            effective = self._effective_dst(dst)
            if effective:
                resolved.append((src, dst, effective))
        if not resolved:
            return
        fdbs = self.bus.request(
            ev.FindRoutesBatchRequest([(s, e) for s, _, e in resolved])
        ).fdbs
        for (src, dst, effective), fdb in zip(resolved, fdbs):
            if fdb:
                true_dst = effective if is_sdn_mpi_addr(dst) else None
                self._add_flows_for_path(fdb, src, dst, true_dst)

    # -- snapshots --------------------------------------------------------

    def window_census(self) -> dict:
        """What is mid-air in the install pipeline right now — the
        flight recorder folds this into every frozen bundle (ISSUE 7)
        so an anomaly shows its in-flight context, not just its
        counters."""
        return {
            "pending_routes": len(self._pending),
            "flushing": self._flushing,
            "inflight_windows": _m_inflight.value,
            "pending_barriers": len(self.recovery._pending),
            "retry_queue": sorted(self.recovery._retries),
            "desired_flows": self.recovery.desired.total(),
            "collectives": len(self.collectives),
        }

    def _current_fdb(self, req: ev.CurrentFDBRequest) -> ev.CurrentFDBReply:
        return ev.CurrentFDBReply(self.fdb)

    def _current_collectives(
        self, req: "ev.CurrentCollectivesRequest"
    ) -> "ev.CurrentCollectivesReply":
        return ev.CurrentCollectivesReply(self.collectives)
