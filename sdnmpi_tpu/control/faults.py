"""Chaos fault-injection harness for the simulated fabric and the wire.

The recovery plane (control/recovery.py) exists for hardware that
fails; this module is the hardware that fails. Two layers:

- :class:`FaultPlan` — a seeded fault schedule attached to the
  simulated :class:`~sdnmpi_tpu.control.fabric.Fabric`
  (``fabric.faults = plan`` / ``plan.attach(fabric)``). The fabric
  consults it on every southbound send (dropped / stalled / truncated
  windows, dropped barrier acks, delayed stats replies), and
  :meth:`FaultPlan.step` drives scenario-level chaos: seeded switch
  crashes + redials, link flaps, and stalled-stream releases.
  :meth:`FaultPlan.quiesce` heals everything — redials every crashed
  switch, restores every flapped link, releases every stalled stream,
  and stops injecting — so a chaos soak can assert the recovery plane
  converged the fabric back to the desired store exactly
  (tests/test_recovery.py).
- :class:`FaultProxy` — a byte-level TCP shim for wire mode: a fake (or
  real) OpenFlow switch dials the proxy, the proxy dials the real
  ``OFSouthbound``, and faults are injected on the actual byte stream —
  frozen forwarding (half-open peer), hard cuts mid-window (crash), and
  truncated frames (a dying switch's last, partial TCP segment).

Nothing here is test-only plumbing in the pejorative sense: ``--chaos``
(sdnmpi_tpu.launch) arms a FaultPlan against the simulated fabric so a
live demo controller can be watched surviving the same schedule.
"""

from __future__ import annotations

import asyncio
import logging
import random

log = logging.getLogger("faults")

#: send-fault kinds a FaultPlan can return for one switch's span
DROP = "drop"  #: the bytes never reach the switch (verdict: dropped)
STALL = "stall"  #: queued behind a frozen stream; applied on release
TRUNCATE = "truncate"  #: a frame boundary is cut mid-span; tail is lost

#: table-mutation kinds (ISSUE 15): silent flow-table corruption behind
#: the controller's back — no event fires, no verdict reports it; ONLY
#: the audit plane's ground-truth sweep (control/audit.py) can see it
MUTATE_KINDS = (
    "drop_row",  #: a desired row vanishes (missing)
    "insert_row",  #: a bogus row appears (orphan)
    "blackhole",  #: a row's actions become drop (missing via mismatch)
    "freeze",  #: a row forwards but its counters die (counter-dead)
)


class FaultPlan:
    """Seeded fault schedule (see module docstring).

    All probabilities are per-opportunity: send faults per per-switch
    span, scenario faults per :meth:`step`. The RNG is the only state
    shared across fault kinds, so one seed reproduces one chaos
    history bit-for-bit.
    """

    def __init__(
        self,
        seed: int = 0,
        p_send_drop: float = 0.0,
        p_send_stall: float = 0.0,
        p_send_truncate: float = 0.0,
        p_ack_drop: float = 0.0,
        p_stats_delay: float = 0.0,
        p_crash: float = 0.0,
        p_redial: float = 0.5,
        p_flap: float = 0.0,
        p_restore: float = 0.5,
        p_release: float = 0.5,
        max_crashed: int = 2,
        p_mutate: float = 0.0,
        mutate_kinds=MUTATE_KINDS,
        mutate_priority: int = 0x8000,
    ) -> None:
        self.rng = random.Random(seed)
        self.p_send_drop = p_send_drop
        self.p_send_stall = p_send_stall
        self.p_send_truncate = p_send_truncate
        self.p_ack_drop = p_ack_drop
        self.p_stats_delay = p_stats_delay
        self.p_crash = p_crash
        self.p_redial = p_redial
        self.p_flap = p_flap
        self.p_restore = p_restore
        self.p_release = p_release
        self.max_crashed = max_crashed
        self.p_mutate = p_mutate
        self.mutate_kinds = tuple(mutate_kinds)
        #: priority of the rows mutations target (the Router's install
        #: priority — Config.priority_default; the audit plane's scope)
        self.mutate_priority = mutate_priority
        self.fabric = None
        self.active = True
        #: links taken down by step() (not by crashes), awaiting restore
        self.flapped: list[tuple[int, int, int, int]] = []
        #: every injected table mutation: (dpid, kind, (src, dst)) —
        #: the audit soak's ledger (quiesce() deliberately does NOT
        #: repair these: only the audit plane's ground-truth sweep can)
        self.mutations: list[tuple[int, str, tuple[str, str]]] = []
        # injection tallies (the soak prints these beside the registry)
        self.counts: dict[str, int] = {
            DROP: 0, STALL: 0, TRUNCATE: 0, "ack_drop": 0,
            "stats_delay": 0, "crash": 0, "redial": 0, "flap": 0,
            "restore": 0, "mutate": 0,
        }

    def attach(self, fabric) -> "FaultPlan":
        self.fabric = fabric
        fabric.faults = self
        return self

    # -- send-level hooks (consulted by Fabric) ---------------------------

    def send_fault(self, dpid: int) -> str | None:
        """Fault verdict for one switch's span of a send (None = clean)."""
        if not self.active:
            return None
        r = self.rng.random()
        if r < self.p_send_drop:
            self.counts[DROP] += 1
            return DROP
        r -= self.p_send_drop
        if r < self.p_send_stall:
            self.counts[STALL] += 1
            return STALL
        r -= self.p_send_stall
        if r < self.p_send_truncate:
            self.counts[TRUNCATE] += 1
            return TRUNCATE
        return None

    def ack_fault(self, dpid: int) -> bool:
        """True: lose this barrier ack (the install applied, the receipt
        did not — the pure barrier-timeout path)."""
        if self.active and self.rng.random() < self.p_ack_drop:
            self.counts["ack_drop"] += 1
            return True
        return False

    def stats_fault(self, dpid: int) -> bool:
        """True: this stats pull returns nothing (delayed StatsReply)."""
        if self.active and self.rng.random() < self.p_stats_delay:
            self.counts["stats_delay"] += 1
            return True
        return False

    # -- scenario driver --------------------------------------------------

    def step(self) -> None:
        """One chaos step against the attached fabric: maybe crash a
        switch, maybe redial a crashed one, maybe flap or restore a
        link, maybe release a stalled stream. Seeded, so a failing soak
        replays exactly."""
        fabric = self.fabric
        assert fabric is not None, "attach() a fabric first"
        rng = self.rng
        if (
            len(fabric._crashed) < self.max_crashed
            and fabric.switches and rng.random() < self.p_crash
        ):
            dpid = rng.choice(sorted(fabric.switches))
            self.counts["crash"] += 1
            fabric.crash_switch(dpid)
        for dpid in sorted(fabric._crashed):
            if rng.random() < self.p_redial:
                self.counts["redial"] += 1
                fabric.redial_switch(dpid)
        if fabric.links and rng.random() < self.p_flap:
            link = rng.choice(sorted(fabric.links))
            self.counts["flap"] += 1
            fabric.remove_link(*link)
            self.flapped.append(link)
        for link in list(self.flapped):
            if rng.random() < self.p_restore:
                a, pa, b, pb = link
                self.flapped.remove(link)
                if a in fabric.switches and b in fabric.switches:
                    self.counts["restore"] += 1
                    fabric.add_link(a, pa, b, pb)
                # else: an endpoint crashed meanwhile; its redial's dark-
                # link pass cannot know about flap-removed links, so
                # requeue until both ends are back
                else:
                    self.flapped.append(link)
        for dpid in sorted(fabric._stall_q):
            if rng.random() < self.p_release:
                fabric.release_stalls(dpid)
        if self.p_mutate > 0 and rng.random() < self.p_mutate:
            self.mutate()

    # -- table mutations (ISSUE 15) ---------------------------------------

    def mutate(self, dpid: int | None = None,
               kind: str | None = None) -> tuple | None:
        """Inject ONE silent flow-table mutation behind the
        controller's back (see MUTATE_KINDS) and record it in the
        ledger. No bus event fires and no verdict reports it — exactly
        the divergence class only the audit plane's OFPST_FLOW sweep
        can detect. A row is mutated at most once (re-mutating a row
        the audit already healed would make the soak's
        one-divergence-per-mutation accounting ambiguous). Returns the
        ledger record, or None when no eligible row exists."""
        from sdnmpi_tpu.protocol import openflow as of

        fabric = self.fabric
        assert fabric is not None, "attach() a fabric first"
        rng = self.rng
        kind = kind or rng.choice(self.mutate_kinds)
        mutated = {(d, row) for d, _k, row in self.mutations}

        if kind == "insert_row":
            if not fabric.switches:
                return None
            dpid = rng.choice(sorted(fabric.switches)) if dpid is None \
                else dpid
            # a bogus exact-match row the controller never desired —
            # locally-administered MACs from a range no generator host
            # or vMAC uses, so the row is inert in the data plane
            while True:
                src = "0a:fa:00:00:%02x:%02x" % (
                    rng.randrange(256), rng.randrange(256)
                )
                dst = "0a:fb:00:00:%02x:%02x" % (
                    rng.randrange(256), rng.randrange(256)
                )
                if (dpid, (src, dst)) not in mutated:
                    break
            fabric.switches[dpid].flow_mod(of.FlowMod(
                match=of.Match(dl_src=src, dl_dst=dst),
                actions=(of.ActionOutput(1),),
                priority=self.mutate_priority,
            ))
        else:
            def eligible(e) -> bool:
                return (
                    e.priority == self.mutate_priority
                    and e.match.dl_src is not None
                    and e.match.dl_dst is not None
                    and e.cookie == 0
                    and not e.frozen and e.actions != ()
                )

            def rows_of(d):
                return [
                    e for e in fabric.switches[d].flow_table
                    if eligible(e) and (
                        d, (e.match.dl_src, e.match.dl_dst)
                    ) not in mutated
                ]

            if dpid is None:
                candidates = [
                    d for d in sorted(fabric.switches) if rows_of(d)
                ]
                if not candidates:
                    return None
                dpid = rng.choice(candidates)
            rows = rows_of(dpid)
            if not rows:
                return None
            e = rng.choice(rows)
            src, dst = e.match.dl_src, e.match.dl_dst
            if kind == "drop_row":
                fabric.switches[dpid].drop_entries({id(e)})
            elif kind == "blackhole":
                e.actions = ()
            elif kind == "freeze":
                e.frozen = True
            else:
                raise ValueError(f"unknown mutation kind {kind!r}")
        rec = (dpid, kind, (src, dst))
        self.mutations.append(rec)
        self.counts["mutate"] += 1
        return rec

    def quiesce(self) -> None:
        """Heal the world and stop injecting: every surviving fault is
        repaired so the recovery plane's convergence can be asserted
        against a quiet fabric. Table mutations are deliberately NOT
        repaired — no controller-side machinery ever learns about them
        except the audit plane's ground-truth sweep, so leaving them in
        place is exactly what the audit soak asserts against."""
        fabric = self.fabric
        self.active = False
        for dpid in sorted(fabric._crashed):
            fabric.redial_switch(dpid)
        for a, pa, b, pb in self.flapped:
            if a in fabric.switches and b in fabric.switches:
                fabric.add_link(a, pa, b, pb)
        self.flapped.clear()
        fabric.release_stalls()


class FaultProxy:
    """Byte-level TCP fault shim for wire mode (see module docstring).

    One proxy fronts ONE switch connection: the switch dials
    ``serve()``'s port, the proxy dials ``upstream_port`` (the real
    OFSouthbound), and two pump tasks forward bytes. Faults:

    - ``freeze()`` / ``thaw()`` — stop/resume forwarding in both
      directions while keeping both sockets open: the half-open peer
      the controller-side echo keepalive exists to kill;
    - ``cut()`` — abort both sides mid-stream: a switch crash from the
      controller's point of view;
    - ``truncate_to_switch_next`` — the next controller->switch chunk
      loses its tail half mid-frame, then the connection drops: the
      classic dying-switch partial segment.
    """

    def __init__(self, upstream_port: int, host: str = "127.0.0.1"):
        self.host = host
        self.upstream_port = upstream_port
        self.server: asyncio.AbstractServer | None = None
        self.frozen = False
        self.truncate_to_switch_next = False
        self._held: list[tuple[asyncio.StreamWriter, bytes]] = []
        self._writers: list[asyncio.StreamWriter] = []
        self.bytes_to_switch = 0
        self.bytes_to_controller = 0

    async def serve(self) -> int:
        self.server = await asyncio.start_server(self._handle, self.host, 0)
        return self.server.sockets[0].getsockname()[1]

    async def _handle(self, sw_reader, sw_writer) -> None:
        up_reader, up_writer = await asyncio.open_connection(
            self.host, self.upstream_port
        )
        self._writers += [sw_writer, up_writer]
        await asyncio.gather(
            self._pump(sw_reader, up_writer, to_switch=False),
            self._pump(up_reader, sw_writer, to_switch=True),
            return_exceptions=True,
        )

    async def _pump(self, reader, writer, to_switch: bool) -> None:
        try:
            while True:
                data = await reader.read(65536)
                if not data:
                    break
                if to_switch and self.truncate_to_switch_next:
                    # deliver a partial frame, then die mid-connection
                    self.truncate_to_switch_next = False
                    writer.write(data[: max(1, len(data) // 2)])
                    await writer.drain()
                    self.cut()
                    return
                if self.frozen:
                    self._held.append((writer, data))
                    continue
                if to_switch:
                    self.bytes_to_switch += len(data)
                else:
                    self.bytes_to_controller += len(data)
                writer.write(data)
                await writer.drain()
        except (ConnectionError, RuntimeError):
            pass
        finally:
            if not self.frozen:
                try:
                    writer.close()
                except RuntimeError:
                    pass

    def freeze(self) -> None:
        self.frozen = True

    async def thaw(self) -> None:
        self.frozen = False
        held, self._held = self._held, []
        for writer, data in held:
            writer.write(data)
            await writer.drain()

    def cut(self) -> None:
        for w in self._writers:
            try:
                w.transport.abort()
            except RuntimeError:
                pass
        self._writers.clear()
        self._held.clear()

    async def close(self) -> None:
        self.cut()
        if self.server is not None:
            self.server.close()
            await self.server.wait_closed()
