"""Packet-level topology discovery: LLDP link probing + host learning.

The reference gets its link map from Ryu's ``switches`` app under
``--observe-links`` (reference: run_router.sh:2): the controller floods
an LLDP frame out of every switch port; when the frame packet-ins back
from the adjacent switch, the (origin, arrival) pair is a directed link
(consumed at reference: sdnmpi/topology.py:184-202). Hosts are learned
from the source MAC of ordinary traffic arriving on non-link ports
(Ryu's host tracker behind EventHostAdd, reference: topology.py:200-202).

This app is that mechanism for the simulated fabric: with
``Fabric(discovery="packet")`` the fabric announces only what a real OF
channel would (datapath up + port sets from the handshake) and the
controller must *earn* the link/host map from actual frames — the same
``EventLinkAdd``/``EventHostAdd`` stream the direct mode publishes,
produced from bytes instead. tests/test_discovery.py asserts the two
modes converge to identical TopologyDB state.

Switch/port knowledge rides EventSwitchEnter/EventPortAdd (the OF
features/port-status channel, legitimately switch-reported — LLDP is
only about LINKS); link *deletion* likewise stays event-driven (port
down / switch leave), as in Ryu where LLDP timeout merely approximates
what port-status reports directly.
"""

from __future__ import annotations

import logging

from sdnmpi_tpu.config import Config, DEFAULT_CONFIG
from sdnmpi_tpu.control import events as ev
from sdnmpi_tpu.control.bus import EventBus
from sdnmpi_tpu.core.topology_db import Host, Link, Port
from sdnmpi_tpu.protocol import openflow as of
from sdnmpi_tpu.protocol.lldp import decode_lldp, encode_lldp

log = logging.getLogger("LLDPDiscovery")


class LLDPDiscovery:
    name = "LLDPDiscovery"

    def __init__(
        self,
        bus: EventBus,
        southbound,
        config: Config = DEFAULT_CONFIG,
    ) -> None:
        self.bus = bus
        self.southbound = southbound
        self.config = config
        #: dpid -> known port numbers (from the OF handshake events)
        self.ports: dict[int, set[int]] = {}
        #: directed links already announced: (src_dpid, src_port, dst_dpid, dst_port)
        self.links: set[tuple[int, int, int, int]] = set()
        #: (dpid, port_no) known to face another switch — never host ports
        self.link_ports: set[tuple[int, int]] = set()
        #: announced hosts: mac -> (dpid, port_no); location tracked so a
        #: re-attached host is re-announced (TopologyDB.add_host upserts)
        self.hosts: dict[str, tuple[int, int]] = {}

        bus.subscribe(ev.EventSwitchEnter, self._ports_changed)
        bus.subscribe(ev.EventPortAdd, self._ports_changed)
        bus.subscribe(ev.EventSwitchLeave, self._switch_leave)
        bus.subscribe(ev.EventLinkDelete, self._link_delete)
        bus.subscribe(ev.EventPacketIn, self._packet_in)

    # -- probing -----------------------------------------------------------

    def probe(self, dpid: int | None = None) -> None:
        """Flood LLDP out of every known port (of one switch, or all).
        Each probe that crosses a live inter-switch link packet-ins back
        from the far side and becomes an EventLinkAdd."""
        targets = [dpid] if dpid is not None else sorted(self.ports)
        for d in targets:
            for port_no in sorted(self.ports.get(d, ())):
                self._send_probe(d, port_no)

    def _send_probe(self, dpid: int, port_no: int) -> None:
        self.southbound.packet_out(
            dpid,
            of.PacketOut(
                data=encode_lldp(dpid, port_no),
                actions=(of.ActionOutput(port_no),),
            ),
        )

    # -- port bookkeeping --------------------------------------------------

    def _ports_changed(self, event) -> None:
        sw = event.switch
        dpid = sw.dp.id  # Ryu-shaped entity (core/topology_db.py:72-77)
        self.ports[dpid] = {p.port_no for p in sw.ports}
        # probe ALL of the switch's ports, not just unseen port numbers:
        # a link re-cabled onto a previously-known port must be
        # re-discovered too (re-learning an existing link is a deduped
        # no-op, so the extra probes are harmless)
        self.probe(dpid)

    def _rebuild_link_ports(self) -> None:
        self.link_ports = {(l[0], l[1]) for l in self.links} | {
            (l[2], l[3]) for l in self.links
        }

    def _switch_leave(self, event) -> None:
        dpid = event.switch.dp.id
        self.ports.pop(dpid, None)
        self.links = {l for l in self.links if dpid not in (l[0], l[2])}
        self._rebuild_link_ports()
        # forget hosts on the dead switch so they re-announce on their
        # next packet from wherever they re-attach
        self.hosts = {m: loc for m, loc in self.hosts.items() if loc[0] != dpid}

    def _link_delete(self, event) -> None:
        link = event.link
        key = (link.src.dpid, link.src.port_no, link.dst.dpid, link.dst.port_no)
        self.links.discard(key)
        # freed ports may now face hosts; stop classifying them as transit
        self._rebuild_link_ports()

    # -- packet-in ---------------------------------------------------------

    def _packet_in(self, event: ev.EventPacketIn) -> None:
        pkt = event.pkt
        if pkt.eth_type == of.ETH_TYPE_LLDP:
            try:
                src_dpid, src_port = decode_lldp(pkt)
            except ValueError:
                log.debug("ignoring foreign LLDP frame")
                return
            self._learn_link(src_dpid, src_port, event.dpid, event.in_port)
            return
        self._learn_host(pkt.eth_src, event.dpid, event.in_port)

    def _learn_link(
        self, src_dpid: int, src_port: int, dst_dpid: int, dst_port: int
    ) -> None:
        key = (src_dpid, src_port, dst_dpid, dst_port)
        self.link_ports.add((src_dpid, src_port))
        self.link_ports.add((dst_dpid, dst_port))
        if key in self.links:
            return
        self.links.add(key)
        self.bus.publish(
            ev.EventLinkAdd(
                Link(Port(src_dpid, src_port), Port(dst_dpid, dst_port))
            )
        )

    def _learn_host(self, mac: str, dpid: int, in_port: int) -> None:
        if self.hosts.get(mac) == (dpid, in_port):
            return  # already announced at this location
        first_octet = int(mac[:2], 16)
        if first_octet & 0x01:  # broadcast/multicast source: never a host
            return
        if (dpid, in_port) in self.link_ports:
            return  # traffic transiting an inter-switch port
        # first sighting, or the host moved: (re-)announce — the
        # TopologyDB upserts host locations by MAC
        self.hosts[mac] = (dpid, in_port)
        self.bus.publish(ev.EventHostAdd(Host(mac, Port(dpid, in_port))))
