from sdnmpi_tpu.control.bus import EventBus  # noqa: F401
from sdnmpi_tpu.control.fabric import Fabric, SimHost, SimSwitch  # noqa: F401
from sdnmpi_tpu.control.router import Router  # noqa: F401
from sdnmpi_tpu.control.topology_manager import TopologyManager  # noqa: F401
from sdnmpi_tpu.control.process_manager import ProcessManager  # noqa: F401
from sdnmpi_tpu.control.monitor import Monitor  # noqa: F401
from sdnmpi_tpu.control.controller import Controller  # noqa: F401
