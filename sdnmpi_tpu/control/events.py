"""Event and request/reply types for the control-plane bus.

Mirrors the reference's Ryu event vocabulary: discovery events
(ryu.topology.event consumed at reference: sdnmpi/topology.py:184-202),
datapath lifecycle (EventOFPStateChange, reference: sdnmpi/router.py:69-81),
packet-in, and the app-level request/reply pairs
(reference: sdnmpi/topology.py:12-56, sdnmpi/process.py:15-50,
sdnmpi/router.py:16-34).

Two deliberate upgrades over the reference:
- ``FindAllRoutesRequest`` actually works here (the reference's reply class
  crashes on an undefined variable and its handler replies with the wrong
  type — sdnmpi/topology.py:48,147).
- ``FindRoutesBatchRequest`` resolves an entire collective's rank-pair
  batch in one oracle call — the request the TPU backend exists for.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from sdnmpi_tpu.protocol.openflow import Packet


class Event:
    """Base class for async pub/sub events."""


class Request:
    """Base class for sync request/reply exchanges; ``dst`` names the
    app that answers, as in Ryu's send_request addressing."""

    dst: str


class Reply:
    pass


# -- datapath / discovery -------------------------------------------------


@dataclasses.dataclass
class EventDatapathUp(Event):
    dpid: int


@dataclasses.dataclass
class EventDatapathDown(Event):
    dpid: int


@dataclasses.dataclass
class EventSwitchEnter(Event):
    switch: Any


@dataclasses.dataclass
class EventSwitchLeave(Event):
    switch: Any


@dataclasses.dataclass
class EventPortAdd(Event):
    """A known switch grew a port (Ryu's EventPortAdd plays this role).
    Carries the switch's refreshed entity so TopologyDB can upsert its
    port set; deliberately distinct from EventSwitchEnter so the RPC
    mirror does not re-broadcast ``add_switch`` for every cabling change
    (the reference's feed announces a switch once,
    sdnmpi/rpc_interface.py:56-60)."""

    switch: Any


@dataclasses.dataclass
class EventPortDelete(Event):
    """A switch lost a port (cable pulled / admin down): the real
    southbound maps OFPT_PORT_STATUS deletes here (Ryu's EventPortDelete
    role). TopologyManager prunes every link riding the port."""

    dpid: int
    port_no: int


@dataclasses.dataclass
class EventLinkAdd(Event):
    link: Any


@dataclasses.dataclass
class EventLinkDelete(Event):
    link: Any


@dataclasses.dataclass
class EventHostAdd(Event):
    host: Any


@dataclasses.dataclass
class EventTopologyChanged(Event):
    """Coalesced "the graph changed" signal, published once per logical
    mutation (a link with both directed halves, a switch with all its
    links) so flow revalidation runs once, not once per sub-event."""


@dataclasses.dataclass
class EventPacketIn(Event):
    dpid: int
    in_port: int
    pkt: Packet
    buffer_id: int


# -- topology manager (reference: sdnmpi/topology.py:12-56) ---------------


@dataclasses.dataclass
class CurrentTopologyRequest(Request):
    dst = "TopologyManager"


@dataclasses.dataclass
class CurrentTopologyReply(Reply):
    topology: Any


@dataclasses.dataclass
class FindRouteRequest(Request):
    dst = "TopologyManager"
    src_mac: str
    dst_mac: str


@dataclasses.dataclass
class FindRouteReply(Reply):
    fdb: list


@dataclasses.dataclass
class FindAllRoutesRequest(Request):
    dst = "TopologyManager"
    src_mac: str
    dst_mac: str


@dataclasses.dataclass
class FindAllRoutesReply(Reply):
    fdbs: list
    #: True when enumeration stopped at Config.max_enumerated_paths —
    #: ``fdbs`` is a prefix of the (possibly exponential) full path set
    truncated: bool = False


@dataclasses.dataclass
class FindRoutesBatchRequest(Request):
    dst = "TopologyManager"
    pairs: list  # [(src_mac, dst_mac), ...]
    #: routing policy for the batch:
    #: - "shortest": deterministic next-hop paths (cached APSP)
    #: - "balanced": load-aware ECMP spread, seeded with the measured
    #:   link utilization the Monitor feeds the TopologyManager
    #: - "adaptive": UGAL min/non-min — flows may detour through a
    #:   Valiant intermediate when the minimal DAG is congested
    policy: str = "shortest"


@dataclasses.dataclass
class FindRoutesBatchReply(Reply):
    fdbs: list
    #: max directed-link load of the batch's chosen paths (balanced mode)
    max_congestion: float = 0.0


@dataclasses.dataclass
class DispatchRoutesBatchRequest(Request):
    """Split-phase route resolution: the oracle's device program for the
    batch is *launched* and the reply returns immediately with an
    in-flight :class:`~sdnmpi_tpu.oracle.batch.RouteWindow`; the caller
    reaps (host decode) later, overlapping the next window's device
    compute — the dispatch leg of the pipelined install plane
    (control/router.py flush_routes). Same pair/policy contract as
    :class:`FindRoutesBatchRequest`."""

    dst = "TopologyManager"
    pairs: list  # [(src_mac, dst_mac), ...]
    policy: str = "shortest"
    #: dirtied-switch dpid set of the delta-narrowed churn dataflow
    #: (None = plain batch). With ``policy="shortest"`` the oracle
    #: re-scores the pairs against the incrementally-repaired APSP with
    #: the set as a device mask tensor, and the reaped window's
    #: ``touched`` array marks pairs whose new path crosses it — the
    #: Router's drain-attribution telemetry
    #: (TopologyDB.find_routes_batch_delta_dispatch).
    dirty: Any = None


@dataclasses.dataclass
class DispatchRoutesBatchReply(Reply):
    window: Any  # oracle.batch.RouteWindow -> WindowRoutes


@dataclasses.dataclass
class UtilEpochRequest(Request):
    """Published-epoch counter of the device utilization plane (0 when
    no plane is configured). Flow revalidation reads it to skip
    recomputes when neither the topology nor the utilization state
    moved since its last pass (control/router.py)."""

    dst = "TopologyManager"


@dataclasses.dataclass
class UtilEpochReply(Reply):
    epoch: int


@dataclasses.dataclass
class FindCollectiveRoutesRequest(Request):
    """Array-native whole-collective routing: ``macs`` lists the N unique
    endpoints once, ``src_idx``/``dst_idx`` are [F] int indices into it.
    Replaces F per-pair queries with one request whose reply is a
    ``CollectiveRoutes`` (oracle/batch.py) — no per-pair Python objects
    anywhere on the path. This is the scaled form of the seam the
    reference serves one pair at a time (sdnmpi/topology.py:138-142)."""

    dst = "TopologyManager"
    macs: list
    src_idx: Any  # [F] int array
    dst_idx: Any  # [F] int array
    policy: str = "balanced"
    #: device-side phase scheduler leg (ISSUE 8): not-None routes the
    #: collective as a *phased flow program* — the pair set packs into
    #: phases on device (sdnmpi_tpu/sched) and the reply's ``routes``
    #: is a ``PhasedFlowProgram`` whose per-phase windows are already
    #: dispatched (reap phase k while k+1..K compute). 0 = auto phase
    #: count, > 0 = that many (pow2-rounded). None = the flat
    #: single-shot batch, bit-identical to the pre-scheduler path.
    schedule: Any = None


@dataclasses.dataclass
class FindCollectiveRoutesReply(Reply):
    routes: Any  # oracle.batch.CollectiveRoutes


@dataclasses.dataclass
class BroadcastRequest(Request):
    dst = "TopologyManager"
    pkt: Packet
    src_dpid: int
    src_in_port: int


@dataclasses.dataclass
class BroadcastReply(Reply):
    pass


# -- process manager (reference: sdnmpi/process.py:15-50) -----------------


@dataclasses.dataclass
class EventProcessAdd(Event):
    rank: int
    mac: str


@dataclasses.dataclass
class EventProcessDelete(Event):
    rank: int


@dataclasses.dataclass
class RankResolutionRequest(Request):
    dst = "ProcessManager"
    rank: int


@dataclasses.dataclass
class RankResolutionReply(Reply):
    mac: Optional[str]


@dataclasses.dataclass
class CurrentProcessAllocationRequest(Request):
    dst = "ProcessManager"


@dataclasses.dataclass
class CurrentProcessAllocationReply(Reply):
    processes: Any


# -- router (reference: sdnmpi/router.py:16-34) ---------------------------


@dataclasses.dataclass
class EventFDBUpdate(Event):
    dpid: int
    src: str
    dst: str
    port: int


@dataclasses.dataclass
class EventFlowRemoved(Event):
    """A switch expired a flow (idle/hard timeout) and reported it —
    the OFPFF_SEND_FLOW_REM reply the reference requests on every
    install but never handles (reference: sdnmpi/router.py:61; SURVEY
    §2 defect). The Router consumes it to keep SwitchFDB coherent."""

    dpid: int
    match: Any  # protocol.openflow.Match
    priority: int
    reason: int  # protocol.ofwire.OFPRR_*
    duration_sec: float = 0.0
    packet_count: int = 0
    byte_count: int = 0


@dataclasses.dataclass
class EventBarrierAck(Event):
    """A datapath answered the OFPT_BARRIER_REQUEST terminating one of
    its batched install spans (OpenFlow 1.0 §5.3.7: the switch has
    finished processing everything sent before the barrier). The
    recovery plane (control/recovery.py) treats it as the install's
    end-to-end receipt: ack -> barrier_rtt_seconds sample; no ack
    within Config.barrier_timeout_s -> anti-entropy resync."""

    dpid: int
    xid: int


@dataclasses.dataclass
class EventFDBRemove(Event):
    """Emitted when the router tears down a stale flow (no reference
    equivalent — the reference never removes flows, see SURVEY §2)."""

    dpid: int
    src: str
    dst: str


@dataclasses.dataclass
class EventFDBRemoveBatch(Event):
    """One teardown *burst* — a revalidation pass or rank exit tears
    down hundreds of rows at once, and per-row :class:`EventFDBRemove`
    publishes cost one RPC broadcast each. The Router publishes bursts
    as ONE of these (``rows`` is ``[(dpid, src, dst), ...]``); single
    removals (flow expiry, datapath down of a lone flow) keep the
    per-row event. Subscribers that only understand per-row removals
    attach through :func:`subscribe_fdb_removes` — the compat shim that
    expands batches for them."""

    rows: list  # [(dpid, src, dst), ...]


def subscribe_fdb_removes(bus, handler) -> None:
    """Compat shim: deliver every FDB removal — batched or per-row — to
    a per-row ``handler(EventFDBRemove)``. Existing per-row consumers
    subscribe here instead of to :class:`EventFDBRemove` alone and see
    the exact pre-batching event stream."""
    bus.subscribe(EventFDBRemove, handler)
    bus.subscribe(
        EventFDBRemoveBatch,
        lambda e: [handler(EventFDBRemove(*row)) for row in e.rows],
    )


@dataclasses.dataclass
class EventCollectiveInstalled(Event):
    """A whole collective's flows were block-installed proactively (no
    reference equivalent — the reference decodes the collective type but
    only logs it, sdnmpi/router.py:182). ``cookie`` identifies the
    install for teardown; counts summarize what per-pair FDB events
    would have reported one at a time."""

    cookie: int
    coll_type: int
    n_pairs: int
    n_flows: int  # switch-level flow entries across all blocks
    max_congestion: float


@dataclasses.dataclass
class EventCollectivePhaseInstalled(Event):
    """One phase of a scheduled collective's phased flow program hit
    the wire (ISSUE 8) — the phase-boundary event: its install window
    has been sent (and its barrier xids registered with the recovery
    plane; the ack drains asynchronously while phase+1 reaps).
    ``phase`` ascends 0..n_phases-1 in program order; the final phase
    is followed by the program-level :class:`EventCollectiveInstalled`."""

    cookie: int
    phase: int
    n_phases: int
    n_pairs: int  # rank pairs routed in this phase
    n_flows: int  # switch-level flow entries this phase installed
    max_congestion: float  # the phase's discrete max-link load


@dataclasses.dataclass
class EventCollectiveRemoved(Event):
    cookie: int


@dataclasses.dataclass
class CurrentFDBRequest(Request):
    dst = "Router"


@dataclasses.dataclass
class CurrentFDBReply(Reply):
    fdb: Any


@dataclasses.dataclass
class CurrentCollectivesRequest(Request):
    dst = "Router"


@dataclasses.dataclass
class CurrentCollectivesReply(Reply):
    collectives: Any  # core.collective_table.CollectiveTable


# -- telemetry ------------------------------------------------------------


@dataclasses.dataclass
class TelemetryRequest(Request):
    """Snapshot of the control-plane telemetry registry (counters,
    gauges, histograms, oracle latency summary). Provided by the
    Controller; the RPC mirror requests one per Monitor pass
    (EventStatsFlush) and broadcasts it as ``update_telemetry`` so the
    visualizer and the Prometheus text exposition (api/telemetry.py)
    always report the same values from the same registry."""

    dst = "Controller"


@dataclasses.dataclass
class TelemetryReply(Reply):
    telemetry: dict


@dataclasses.dataclass
class SpanTreeRequest(Request):
    """Resolve one span id to the completed span tree containing it —
    the pull half of exemplar resolution (ISSUE 7): a Prometheus
    histogram bucket's exemplar span id comes back as the full request
    trace from the flight recorder. Provided by the Controller;
    ``tree`` is None when the id fell out of the bounded ring (or no
    recorder is armed)."""

    dst = "Controller"
    span_id: int


@dataclasses.dataclass
class SpanTreeReply(Reply):
    tree: Optional[dict]


@dataclasses.dataclass
class FlightDumpRequest(Request):
    """Freeze a diagnostic bundle NOW (trigger="manual") — the pull-
    mode twin of the anomaly triggers' automatic freeze. Provided by
    the Controller; the bundle is {} when no recorder is armed."""

    dst = "Controller"


@dataclasses.dataclass
class FlightDumpReply(Reply):
    bundle: dict


@dataclasses.dataclass
class TimelineRequest(Request):
    """The metrics timeline's queryable history (ISSUE 14,
    utils/timeline.py): ``{series: {name: [[ts, value], ...]}, ...}``
    over the bounded multi-resolution ring — minutes of per-flush
    metric history beside the flight recorder's short trigger window.
    ``names`` filters to specific series (None = everything). Provided
    by the Controller; the ``timeline()`` pull RPC rides it."""

    names: Optional[list] = None
    dst = "Controller"


@dataclasses.dataclass
class TimelineReply(Reply):
    timeline: dict


@dataclasses.dataclass
class TrafficMatrixRequest(Request):
    """The published measured traffic matrix (ISSUE 19,
    oracle/trafficplane.py): per-tenant src->dst byte rates recovered
    from the audit plane's flow-stats deltas, pod-aggregated under the
    hierarchical oracle. Provided by the Controller; the
    ``traffic_matrix()`` pull RPC rides it. Cells are
    ``[tenant, src_endpoint, dst_endpoint, bps]``; mode is "off" when
    the plane is disabled."""

    dst = "Controller"


@dataclasses.dataclass
class TrafficMatrixReply(Reply):
    matrix: dict


@dataclasses.dataclass
class CongestionReportRequest(Request):
    """The device-side congestion analytics of the latest Monitor pass
    (ISSUE 7): top-k hot links, per-collective attribution (which
    installed collectives ride them), and the discrete-vs-fractional
    congestion figures. Provided by the TopologyManager; {} before the
    first analytics pass (or without a utilization plane)."""

    dst = "TopologyManager"


@dataclasses.dataclass
class CongestionReportReply(Reply):
    report: dict


@dataclasses.dataclass
class EventAnomaly(Event):
    """The flight recorder froze a diagnostic bundle: an anomaly
    trigger fired (latency threshold, p99 regression, recovery
    escalation, barrier timeout). ``summary`` is the bundle minus its
    bulky members (span trees / snapshots stay in the recorder and the
    dump file at ``path``); the RPC mirror broadcasts it as an
    ``anomaly`` notification."""

    trigger: str
    summary: dict
    path: Optional[str] = None


# -- monitor --------------------------------------------------------------


@dataclasses.dataclass
class EventPortStats(Event):
    """Per-port throughput sample (the reference logs these as TSV,
    sdnmpi/monitor.py:87-88; here they also feed the congestion tensor)."""

    dpid: int
    port_no: int
    rx_pps: float
    rx_bps: float
    tx_pps: float
    tx_bps: float


@dataclasses.dataclass
class EventStatsFlush(Event):
    """End of one Monitor sampling pass: every EventPortStats of the
    pass has been published. Utilization consumers use this edge to
    flush their staged samples as ONE vectorized batch (the device
    utilization plane scatters once per pass, not once per port)."""


# -- active/active replica pair (ISSUE 20) --------------------------------


@dataclasses.dataclass
class EventPeerLeaseExpired(Event):
    """A peer replica's lease lapsed (no heartbeat for the timeout):
    this controller is about to adopt its shards. Flight-recorder
    breadcrumb for the failover timeline."""

    replica: int


@dataclasses.dataclass
class EventShardAdopted(Event):
    """One shard of the switch partition changed hands: ``replica``
    now serves ``shard`` at the bumped fencing ``epoch`` — every
    subsequent FlowMod to the shard carries the new epoch cookie."""

    shard: int
    epoch: int
    replica: int


@dataclasses.dataclass
class EventSnapshotColdStart(Event):
    """A checkpoint restore was abandoned (version or digest mismatch)
    and the controller is starting cold instead of crash-looping —
    reactive discovery re-teaches it the fabric (ISSUE 20 satellite)."""

    reason: str


@dataclasses.dataclass
class ReplicaStatusRequest(Request):
    """The replica plane's replication/failover posture: ownership
    map, sequence numbers, lag, lease state. Provided by the
    Controller; the ``replica_status`` pull RPC rides it. Mode is
    "off" on a single controller (``--replica-peer`` unset)."""

    dst = "Controller"


@dataclasses.dataclass
class ReplicaStatusReply(Reply):
    status: dict
