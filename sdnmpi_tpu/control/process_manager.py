"""MPI process manager app.

Equivalent of the reference's ``ProcessManager``
(reference: sdnmpi/process.py:53-119): installs the announcement-intercept
flow on every switch (UDP dport 61000 -> controller at control priority),
parses LAUNCH/EXIT announcement broadcasts into the RankAllocationDB, and
answers rank-resolution queries for the router.
"""

from __future__ import annotations

import logging

from sdnmpi_tpu.config import Config, DEFAULT_CONFIG
from sdnmpi_tpu.control import events as ev
from sdnmpi_tpu.control.bus import EventBus
from sdnmpi_tpu.core.rank_allocation_db import RankAllocationDB
from sdnmpi_tpu.protocol import openflow as of
from sdnmpi_tpu.protocol.announcement import AnnouncementType
from sdnmpi_tpu.utils.mac import BROADCAST_MAC

log = logging.getLogger("ProcessManager")


class ProcessManager:
    name = "ProcessManager"

    def __init__(
        self,
        bus: EventBus,
        southbound,
        config: Config = DEFAULT_CONFIG,
    ) -> None:
        self.bus = bus
        self.southbound = southbound
        self.config = config
        self.rankdb = RankAllocationDB()

        bus.subscribe(ev.EventDatapathUp, self._datapath_up)
        bus.subscribe(ev.EventPacketIn, self._packet_in)
        bus.provide(ev.RankResolutionRequest, self._rank_resolution)
        bus.provide(ev.CurrentProcessAllocationRequest, self._current_allocation)

    def _datapath_up(self, event: ev.EventDatapathUp) -> None:
        # announcement packets -> controller (reference: process.py:61-79)
        mod = of.FlowMod(
            match=of.Match(
                dl_type=of.ETH_TYPE_IP,
                nw_proto=of.IPPROTO_UDP,
                tp_dst=self.config.announcement_port,
            ),
            actions=(of.ActionOutput(of.OFPP_CONTROLLER),),
            priority=self.config.priority_control,
        )
        self.southbound.flow_mod(event.dpid, mod)

    def _packet_in(self, event: ev.EventPacketIn) -> None:
        pkt = event.pkt
        # broadcast + IP only (reference: process.py:87-89)
        if pkt.eth_dst != BROADCAST_MAC or pkt.eth_type != of.ETH_TYPE_IP:
            return
        if pkt.udp_dst != self.config.announcement_port:
            return
        # batch-parse the datagram with the native wire codec: a payload
        # may coalesce many records (an MPI runtime launching thousands
        # of ranks batches its announcements; the reference parses only
        # a single fixed-size record, sdnmpi/process.py:101-105).
        # Malformed records are dropped by the decoder.
        from sdnmpi_tpu.native import decode_announcements

        types, ranks = decode_announcements(pkt.payload)
        if len(types) == 0:
            log.warning("malformed announcement from %s", pkt.eth_src)
            return
        for type_code, rank in zip(types, ranks):
            if type_code == AnnouncementType.LAUNCH:
                self.rankdb.add_process(int(rank), pkt.eth_src)
                self.bus.publish(ev.EventProcessAdd(int(rank), pkt.eth_src))
                log.info("MPI process %s started at %s", rank, pkt.eth_src)
            elif type_code == AnnouncementType.EXIT:
                self.rankdb.delete_process(int(rank))
                self.bus.publish(ev.EventProcessDelete(int(rank)))
                log.info("MPI process %s exited at %s", rank, pkt.eth_src)

    def _rank_resolution(self, req: ev.RankResolutionRequest) -> ev.RankResolutionReply:
        return ev.RankResolutionReply(self.rankdb.get_mac(req.rank))

    def _current_allocation(
        self, req: ev.CurrentProcessAllocationRequest
    ) -> ev.CurrentProcessAllocationReply:
        return ev.CurrentProcessAllocationReply(self.rankdb)
