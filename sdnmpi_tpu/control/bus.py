"""In-process event bus.

Replaces Ryu's app event machinery (synchronous ``send_request`` /
``reply_to_request`` and pub/sub ``send_event_to_observers`` /
``@set_ev_cls`` — see reference: sdnmpi/router.py:151,185,189 and
sdnmpi/rpc_interface.py:42-72) with a deterministic single-threaded
dispatcher: requests dispatch directly to the one registered handler for
the request type; events fan out synchronously to every subscriber in
registration order. The reference achieves the same data-race-freedom via
eventlet green threads; here it's by construction.
"""

from __future__ import annotations

import logging
from collections import defaultdict
from typing import Callable, Type

from sdnmpi_tpu.control.events import Event, Reply, Request

log = logging.getLogger(__name__)


class EventBus:
    def __init__(self) -> None:
        self._request_handlers: dict[Type[Request], Callable[[Request], Reply]] = {}
        self._subscribers: dict[Type[Event], list[Callable[[Event], None]]] = (
            defaultdict(list)
        )
        #: wildcard observers: called with EVERY published event, after
        #: the typed subscribers (observability taps, e.g. the JSONL
        #: event log — utils/event_log.py)
        self._taps: list[Callable[[Event], None]] = []

    def tap(self, handler: Callable[[Event], None]) -> None:
        self._taps.append(handler)

    # -- request/reply ----------------------------------------------------

    def provide(
        self, request_type: Type[Request], handler: Callable[[Request], Reply]
    ) -> None:
        if request_type in self._request_handlers:
            raise ValueError(f"handler already registered for {request_type.__name__}")
        self._request_handlers[request_type] = handler

    def request(self, req: Request) -> Reply:
        handler = self._request_handlers.get(type(req))
        if handler is None:
            raise LookupError(f"no handler for {type(req).__name__}")
        return handler(req)

    # -- pub/sub ----------------------------------------------------------

    def subscribe(
        self, event_type: Type[Event], handler: Callable[[Event], None]
    ) -> None:
        self._subscribers[event_type].append(handler)

    def publish(self, event: Event) -> None:
        # taps BEFORE subscribers: handlers publish derived events
        # synchronously from inside this dispatch, and the event log must
        # record the cause ahead of its effects for offline causal replay
        for tap in self._taps:
            try:
                tap(event)
            except Exception:
                log.exception("tap %r failed on %s", tap, type(event).__name__)
        for handler in list(self._subscribers[type(event)]):
            try:
                handler(event)
            except Exception:  # one bad observer must not break the rest
                log.exception(
                    "subscriber %r failed on %s", handler, type(event).__name__
                )
