"""Open-loop multi-tenant load harness driver (ISSUE 11).

"Millions of users" is a throughput and tail-latency problem, and an
honest tail needs an **open-loop** generator: arrivals are scheduled
from the offered rate alone, never gated on completions, so when the
controller falls behind the schedule the backlog shows up as queueing
delay in every later sample instead of silently throttling the load
(the coordinated-omission trap a closed-loop driver falls into). Each
request's latency is ``completion_wall - scheduled_arrival``, measured
against the run's virtual schedule.

The driver fires packet-ins at a LIVE controller — the same bus, the
same coalescer windows, the same pipelined install plane and (wire
mode) the same byte codec a real deployment exercises — and reports
per-tenant routes/s and p50/p99/p999. Tenants come in two kinds
matching the Router's two-class coalescer queue:

- ``unicast`` — latency-sensitive single-pair lookups (plain ethernet
  packet-ins between the tenant's hosts);
- ``alltoall`` — bulk MPI pair storms: every ordered rank pair of the
  tenant's ranks as a reactive vMAC packet-in (the reference's serving
  model — one packet-in per pair), cycled for the run's duration.

Completion detection leans on the bus being synchronous: a published
packet-in either parks in the coalescer, is rejected at the admission
gate (visible as a per-tenant rejection-counter delta around the
publish), or completes inline (direct path / a high-water flush inside
the publish). Parked requests complete when the flush the driver ticks
(standing in for the fabric's idle edge) returns with the queue empty.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import numpy as np

from sdnmpi_tpu.protocol import openflow as of
from sdnmpi_tpu.protocol.vmac import CollectiveType, VirtualMac


@dataclasses.dataclass
class TenantSpec:
    """One tenant's offered load.

    ``rate`` is requests per second, open-loop. ``kind`` selects the
    traffic shape (see module docstring). ``macs`` are the tenant's
    hosts (unicast pairs / MPI ranks in order); ``ranks`` maps position
    -> registered rank id for ``alltoall`` tenants."""

    name: str
    rate: float
    n_requests: int
    kind: str = "unicast"  # "unicast" | "alltoall"
    macs: tuple = ()
    ranks: tuple = ()


@dataclasses.dataclass
class TenantReport:
    tenant: str
    offered: int
    completed: int
    rejected: int
    routes_per_s: float
    p50_ms: float
    p99_ms: float
    p999_ms: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _percentiles(lat_s: list) -> tuple[float, float, float]:
    if not lat_s:
        return 0.0, 0.0, 0.0
    arr = np.asarray(lat_s) * 1e3
    p50, p99, p999 = np.percentile(arr, (50, 99, 99.9))
    return float(p50), float(p99), float(p999)


def register_ranks(fabric, config, macs) -> list[int]:
    """Register ``macs`` as MPI ranks 0..n-1 through the real
    announcement path (LAUNCH broadcasts, exactly like a job launcher
    would — reference: sdnmpi/process.py:53-119). Returns the ranks."""
    from sdnmpi_tpu.protocol.announcement import Announcement, AnnouncementType

    ranks = list(range(len(macs)))
    for rank, mac in zip(ranks, macs):
        fabric.hosts[mac].send(of.Packet(
            eth_src=mac,
            eth_dst="ff:ff:ff:ff:ff:ff",
            eth_type=of.ETH_TYPE_IP,
            ip_proto=of.IPPROTO_UDP,
            udp_dst=config.announcement_port,
            payload=Announcement(AnnouncementType.LAUNCH, rank).encode(),
        ))
    return ranks


class LoadGen:
    """Drive a live controller with an open-loop multi-tenant schedule.

    ``run`` owns one run: it pre-builds the merged arrival schedule,
    replays it against the bus (never skipping a late arrival — the
    lateness IS the measurement), ticks the coalescer flush as the idle
    edge, and returns ``{tenant: TenantReport}``."""

    def __init__(self, controller, fabric, tick_s: float = 0.002) -> None:
        self.controller = controller
        self.fabric = fabric
        #: idle-edge cadence: arrivals due within one tick inject
        #: back-to-back, then one flush drains the window — the sim
        #: stand-in for the southbound's burst-drained idle callback
        self.tick_s = tick_s

    # -- schedule ----------------------------------------------------------

    def _requests_for(self, t: TenantSpec) -> list[tuple]:
        """The tenant's request stream: ``(dpid, in_port, pkt)`` tuples
        cycled over its pair set, deterministic per spec."""
        hosts = self.fabric.hosts
        out = []
        if t.kind == "unicast":
            pairs = [
                (a, b) for a in t.macs for b in t.macs if a != b
            ]
            for i in range(t.n_requests):
                src, dst = pairs[i % len(pairs)]
                h = hosts[src]
                out.append((h.dpid, h.port_no, of.Packet(
                    eth_src=src, eth_dst=dst, payload=b"lg",
                )))
        elif t.kind == "alltoall":
            ranks = t.ranks or tuple(range(len(t.macs)))
            pairs = [
                (i, j)
                for i in range(len(ranks))
                for j in range(len(ranks))
                if i != j
            ]
            for i in range(t.n_requests):
                si, di = pairs[i % len(pairs)]
                src = t.macs[si]
                vmac = VirtualMac(
                    CollectiveType.ALLTOALL, ranks[si], ranks[di]
                ).encode()
                h = hosts[src]
                out.append((h.dpid, h.port_no, of.Packet(
                    eth_src=src, eth_dst=vmac, eth_type=of.ETH_TYPE_IP,
                )))
        else:
            raise ValueError(f"unknown tenant kind {t.kind!r}")
        return out

    def schedule(self, tenants: list[TenantSpec]) -> list[tuple]:
        """Merged open-loop arrival schedule:
        ``(sched_t, tenant_name, dpid, in_port, pkt)`` sorted by time.
        Per-tenant arrivals are uniform at the offered rate, phase-
        shifted per tenant so same-rate tenants interleave instead of
        colliding on every tick."""
        events = []
        for k, t in enumerate(tenants):
            gap = 1.0 / t.rate if t.rate > 0 else 0.0
            phase = gap * (k + 1) / (len(tenants) + 1)
            reqs = self._requests_for(t)
            for i, (dpid, port, pkt) in enumerate(reqs):
                events.append((phase + i * gap, t.name, dpid, port, pkt))
        events.sort(key=lambda e: e[0])
        return events

    # -- run ---------------------------------------------------------------

    def run(
        self,
        tenants: list[TenantSpec],
        pace: bool = True,
        now: Optional[callable] = None,
    ) -> dict[str, TenantReport]:
        """Replay the merged schedule; returns per-tenant reports.

        ``pace=False`` injects as fast as the controller drains
        (saturation mode, for throughput ceilings). Latency anchors to
        the scheduled arrival when pacing — lateness against the
        schedule IS the open-loop queueing measurement — and to the
        injection instant in saturation mode, where the schedule is
        deliberately outrun and only time-in-system is meaningful."""
        from sdnmpi_tpu.control import events as ev

        router = self.controller.router
        bus = self.controller.bus
        admission = router.admission
        for t in tenants:
            # bind the tenant's MACs to its NAME unconditionally: the
            # completion accounting below attributes rejections by
            # reading the per-tenant counter around each publish, and
            # an unassigned MAC would reject under its own label —
            # turning every drop into a phantom "completed" route
            for mac in t.macs:
                admission.assign(mac, t.name)
            if t.kind == "alltoall":
                # a vMAC pair whose rank never registered is dropped
                # SILENTLY by the Router (unresolved rank) — that is a
                # harness misconfiguration, not load, so fail loudly
                # instead of corrupting the report
                for rank in t.ranks or range(len(t.macs)):
                    if not bus.request(
                        ev.RankResolutionRequest(int(rank))
                    ).mac:
                        raise ValueError(
                            f"tenant {t.name!r}: rank {rank} is not "
                            "registered (run register_ranks first)"
                        )
        events = self.schedule(tenants)
        lat: dict[str, list] = {t.name: [] for t in tenants}
        rejected: dict[str, int] = {t.name: 0 for t in tenants}
        outstanding: list[tuple[str, float]] = []

        clock = time.perf_counter if now is None else now
        t0 = clock()

        # SLO plane feed (ISSUE 14): the harness owns the arrival
        # schedule, so IT measures the latency a tenant experiences —
        # schedule-anchored lateness, queueing-before-park included
        # (the open-loop half the Router's park-to-install feed cannot
        # see). While the run drives, the harness takes OWNERSHIP of
        # its tenants' feed (slo.harness_feed) so the Router does not
        # also record a park-to-install sample per served request —
        # double-counted good observations would halve the burn
        # fraction. None when the controller has no SLO plane.
        slo = getattr(self.controller, "slo", None)
        fed: set = set()
        if slo is not None:
            fed = {t.name for t in tenants} - slo.harness_feed
            slo.harness_feed |= fed

        def drain(t_done: float) -> None:
            if outstanding and not router._pending:
                for name, sched_t in outstanding:
                    lat[name].append(t_done - sched_t)
                    if slo is not None:
                        slo.observe(name, t_done - sched_t)
                outstanding.clear()

        try:
            for sched_t, name, dpid, port, pkt in events:
                if pace:
                    ahead = sched_t - (clock() - t0)
                    if ahead > 0:
                        # flush whatever is parked before going idle:
                        # the real fabric's idle edge fires between
                        # bursts
                        if router._pending:
                            router.flush_routes()
                        drain(clock() - t0)
                        time.sleep(ahead)
                rej0 = admission.rejections(name)
                t_inject = clock() - t0
                bus.publish(
                    ev.EventPacketIn(dpid, port, pkt, of.OFP_NO_BUFFER)
                )
                t_now = clock() - t0
                if admission.rejections(name) > rej0:
                    rejected[name] += 1
                else:
                    outstanding.append(
                        (name, sched_t if pace else t_inject)
                    )
                # a high-water flush inside the publish (or the direct
                # uncoalesced path) completed everything parked so far
                drain(t_now)
                if router._pending and (
                    t_now - sched_t >= self.tick_s or not pace
                ):
                    router.flush_routes()
                    drain(clock() - t0)
            if router._pending:
                router.flush_routes()
            drain(clock() - t0)
        finally:
            if slo is not None:
                slo.harness_feed -= fed
        elapsed = max(clock() - t0, 1e-9)

        reports = {}
        for t in tenants:
            p50, p99, p999 = _percentiles(lat[t.name])
            reports[t.name] = TenantReport(
                tenant=t.name,
                offered=t.n_requests,
                completed=len(lat[t.name]),
                rejected=rejected[t.name],
                routes_per_s=len(lat[t.name]) / elapsed,
                p50_ms=p50, p99_ms=p99, p999_ms=p999,
            )
        return reports
