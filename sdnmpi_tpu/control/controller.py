"""Controller composition root.

The reference composes its apps through Ryu's ``_CONTEXTS`` dependency
injection, with ``RPCInterface`` as the transitive root
(reference: sdnmpi/rpc_interface.py:19-25; SURVEY §3.1). Here composition
is explicit: one ``Controller`` wires the bus, the four apps, and the
southbound together, in a fixed deterministic order.
"""

from __future__ import annotations

from typing import Optional

from sdnmpi_tpu.config import Config, DEFAULT_CONFIG
from sdnmpi_tpu.control.bus import EventBus
from sdnmpi_tpu.control.monitor import Monitor
from sdnmpi_tpu.control.process_manager import ProcessManager
from sdnmpi_tpu.control.router import Router
from sdnmpi_tpu.control.topology_manager import TopologyManager


class Controller:
    name = "Controller"

    def __init__(
        self,
        southbound,
        config: Config = DEFAULT_CONFIG,
        *,
        ownership=None,
        replica_link=None,
    ) -> None:
        self.config = config
        self.bus = EventBus()
        self.southbound = southbound
        # telemetry snapshot seam: the RPC mirror (and anything else on
        # the bus) reads the process-wide registry through the
        # composition root, so tests can interpose and the reply always
        # carries the controller's own view
        from sdnmpi_tpu.control import events as ev

        self.bus.provide(
            ev.TelemetryRequest,
            lambda req: ev.TelemetryReply(self.telemetry()),
        )

        # Subscription order fixes packet-in handling order; the reference's
        # equivalent order is Ryu's app instantiation order (SURVEY §3.1).
        self.topology_manager = TopologyManager(self.bus, southbound, config)
        self.process_manager = ProcessManager(self.bus, southbound, config)
        self.router = Router(self.bus, southbound, config)
        if hasattr(southbound, "install_highwater"):
            # batched-install backpressure cap (see OFSouthbound)
            southbound.install_highwater = config.install_highwater
        if hasattr(southbound, "send_barriers"):
            # acked installs: barrier-terminated windows (ISSUE 5)
            southbound.send_barriers = config.install_barriers
        if hasattr(southbound, "echo_interval"):
            # controller-side keepalive knobs (the launcher arms the
            # loop; echo_tick is also callable synchronously in tests)
            southbound.echo_interval = config.echo_interval_s
            southbound.echo_timeout = config.echo_timeout_s
        if config.coalesce_routes:
            if hasattr(southbound, "on_idle"):
                # route coalescing: the southbound's burst-drained edge
                # flushes the Router's pending lookups as one batched
                # oracle call (see Router.flush_routes)
                southbound.on_idle = self.router.flush_routes
                self.router.coalesce = True
            else:
                # never half-enable: without an idle edge a lone parked
                # packet would wait forever for a batch companion
                import logging

                logging.getLogger("Controller").warning(
                    "coalesce_routes is on but the southbound has no "
                    "on_idle hook; falling back to direct per-packet "
                    "route resolution"
                )
        self.monitor: Optional[Monitor] = (
            Monitor(self.bus, southbound, config) if config.enable_monitor else None
        )
        # --observe-links equivalent (reference: run_router.sh:2): learn
        # links/hosts from LLDP probes + traffic instead of entity events
        self.discovery = None
        if config.observe_links:
            from sdnmpi_tpu.control.discovery import LLDPDiscovery

            self.discovery = LLDPDiscovery(self.bus, southbound, config)

        # structured JSONL event log: a wildcard bus tap (SURVEY §5)
        self.event_logger = None
        if config.event_log:
            from sdnmpi_tpu.utils.event_log import EventLogger

            self.event_logger = EventLogger(
                config.event_log, max_bytes=config.event_log_max_bytes
            )
            self.bus.tap(self.event_logger)

        # device-runtime telemetry (ISSUE 14, utils/devprof.py):
        # compile-wall histograms + persistent-compile-cache hit/miss
        # counters via jax.monitoring (rare events — no hot-path cost),
        # and device-memory watermark gauges sampled once per Monitor
        # flush. Subscribed BEFORE the flight recorder so the trigger
        # pass (and the timeline row) sees the same pass's fresh sample.
        from sdnmpi_tpu.utils import devprof

        devprof.install_monitoring()
        self.bus.subscribe(
            ev.EventStatsFlush, lambda e: devprof.sample_memory()
        )

        # SLO plane (ISSUE 14, control/slo.py): per-tenant objectives,
        # per-tenant latency histograms fed by the Router at window
        # completion, and one multi-window burn-rate trigger per tenant
        # registered with the flight recorder below.
        self.slo = None
        if config.slo_targets:
            from sdnmpi_tpu.control.slo import SLOPlane

            self.slo = SLOPlane(
                config.slo_targets,
                self.router.admission,
                burn_factor=config.slo_burn_factor,
                slow_flushes=config.slo_slow_flushes,
            )
            self.router.slo = self.slo

        # fabric ground-truth audit plane (ISSUE 15, control/audit.py):
        # per-flush OFPST_FLOW sweeps diff the fabric's actual tables
        # against the desired store and heal confirmed divergence as
        # targeted re-drives. Arms only when the southbound can answer
        # flow stats (the sim Fabric and OFSouthbound both can; duck-
        # typed minimal test stacks cannot). Subscribed BEFORE the
        # flight recorder so the trigger pass sees the same flush's
        # fresh divergence counters.
        self.audit = None
        if config.fabric_audit and hasattr(southbound, "flow_stats"):
            from sdnmpi_tpu.control.audit import AuditPlane

            self.audit = AuditPlane(config, southbound, self.router)
            self.router.audit = self.audit
            # the congestion report's measured-vs-modeled column reads
            # the audit's attribution (TopologyManager._assemble_congestion)
            self.topology_manager.audit = self.audit
            self.bus.subscribe(
                ev.EventStatsFlush, lambda e: self.audit.sweep()
            )

        # measured traffic matrix + shadow route-quality sentinel
        # (ISSUE 19): the audit sweep's attributed byte deltas feed a
        # device-resident per-tenant src->dst rate matrix
        # (oracle/trafficplane.py), and each flush re-scores a paced
        # sample of installed routes against a fresh oracle optimum for
        # that measured matrix (control/sentinel.py). Subscribed AFTER
        # the audit sweep (the flush that feeds the matrix) and BEFORE
        # the flight recorder (the trigger pass must see this flush's
        # divergence counters).
        self.traffic = None
        self.sentinel = None
        if self.audit is not None and config.traffic_plane:
            from sdnmpi_tpu.control.sentinel import RouteSentinel
            from sdnmpi_tpu.oracle.trafficplane import TrafficPlane

            self.traffic = TrafficPlane(
                self.topology_manager.topologydb, config
            )
            self.audit.traffic = self.traffic
            self.sentinel = RouteSentinel(
                config, self.router, self.topology_manager.topologydb,
                self.traffic, audit=self.audit,
            )
            self.bus.subscribe(
                ev.EventStatsFlush, lambda e: self._traffic_tick()
            )

        # active/active replica plane (ISSUE 20): store replication +
        # lease failover, ticking on the same EventStatsFlush edge as
        # the audit sweep above (and before the flight recorder below,
        # so a failover's adoption events land in the same pass's
        # trigger sweep). Default-off: without an ownership map and a
        # peer link nothing is constructed.
        self.ownership = ownership
        self.replica = None
        if ownership is not None and replica_link is not None:
            from sdnmpi_tpu.control.replica import ReplicaPlane

            self.replica = ReplicaPlane(self, ownership, replica_link, config)
            self.bus.subscribe(
                ev.EventStatsFlush, lambda e: self.replica.tick()
            )
        self.bus.provide(ev.ReplicaStatusRequest, self._replica_status)

        # anomaly-armed profiler capture (ISSUE 14): a firing trigger
        # opens a jax.profiler window for profile_capture_s seconds
        self.profile_capture = None
        if config.profile_dump_dir:
            self.profile_capture = devprof.ProfileCapture(
                config.profile_dump_dir, config.profile_capture_s
            )

        # flight recorder (ISSUE 7): bounded span-tree ring + snapshot
        # window + event tail, with anomaly triggers freezing diagnostic
        # bundles. Wired LAST so its per-EventStatsFlush trigger pass
        # observes the same pass's utilization flush, anti-entropy tick,
        # and recovery counters (bus handlers run in subscription order).
        self.flight = None
        if config.flight_recorder:
            from sdnmpi_tpu.utils.flight import (
                FlightRecorder,
                HistogramThreshold,
                P99Regression,
            )

            flight = FlightRecorder(
                max_trees=config.flight_max_trees,
                dump_dir=config.flight_dump_dir,
                # the SLO slow window reads the recorder's snapshot
                # ring: size it to COVER slo_slow_flushes, or a large
                # configured window would silently truncate to the
                # ring depth and page noisier than configured
                max_snapshots=max(32, config.slo_slow_flushes + 1),
            )
            # escalations/timeouts: every increment is an incident
            flight.add_counter_triggers()
            for hist in self.LATENCY_HISTOGRAMS:
                if config.flight_latency_threshold_s > 0:
                    flight.triggers.append(HistogramThreshold(
                        hist, config.flight_latency_threshold_s
                    ))
                if config.flight_p99_factor > 0:
                    flight.triggers.append(P99Regression(
                        hist, factor=config.flight_p99_factor
                    ))
            flight.add_context("topology", self._topology_forensics)
            flight.add_context("windows", self.router.window_census)
            if self.slo is not None:
                # SLO burn triggers + the bundle context naming the
                # burning tenant's dominant pipeline stage (ISSUE 14)
                flight.triggers.extend(self.slo.triggers())
                flight.add_context(
                    "slo", lambda: self.slo.forensics(self.flight)
                )
            if self.audit is not None:
                # fabric divergence is ALWAYS an incident: the frozen
                # bundle's detail names the switch and rows (ISSUE 15)
                flight.triggers.append(self.audit.trigger())
                flight.add_context("audit", self.audit.forensics)
            if self.sentinel is not None:
                # routes-don't-fit-the-traffic: the frozen bundle's
                # detail names the worst (tenant, collective, pod-pair)
                # and the context carries the measured matrix (ISSUE 19)
                flight.triggers.append(self.sentinel.trigger())
                flight.add_context("traffic", self.sentinel.forensics)
            if self.replica is not None:
                # failover forensics: ownership map, sequence numbers,
                # replication lag at the moment a bundle froze (ISSUE 20)
                flight.add_context("replica", self.replica.status)
            flight.on_anomaly = self._publish_anomaly
            flight.arm()
            self.bus.tap(flight.event_tap)
            self.bus.subscribe(
                ev.EventStatsFlush, lambda e: flight.snapshot_tick()
            )
            self.flight = flight

        # metrics timeline (ISSUE 14, utils/timeline.py): one compact
        # row per EventStatsFlush — riding the flight recorder's
        # snapshot tee when armed (the tick already paid for the
        # snapshot), its own subscription otherwise.
        self.timeline = None
        if config.metrics_timeline:
            from sdnmpi_tpu.utils.timeline import MetricsTimeline

            self.timeline = MetricsTimeline(
                maxlen=config.timeline_points
            )
            if self.flight is not None:
                self.flight.on_snapshot = (
                    lambda ts, snap: self.timeline.tick(snap, ts)
                )
            else:
                self.bus.subscribe(
                    ev.EventStatsFlush, lambda e: self.timeline.tick()
                )
        if self.profile_capture is not None:
            # close an expired capture window on the flush AFTER the
            # flight recorder's trigger pass (which may have opened it)
            self.bus.subscribe(
                ev.EventStatsFlush,
                lambda e: self.profile_capture.tick(),
            )
        self.bus.provide(ev.SpanTreeRequest, self._span_tree)
        self.bus.provide(ev.FlightDumpRequest, self._flight_dump)
        self.bus.provide(ev.TimelineRequest, self._timeline)
        self.bus.provide(ev.TrafficMatrixRequest, self._traffic_matrix)

    #: the route/install/re-route latency histograms the flight
    #: recorder's latency/p99 triggers watch (ISSUE 7)
    LATENCY_HISTOGRAMS = (
        "install_e2e_seconds",
        "pipeline_install_seconds",
        "reval_rescore_seconds",
        "reval_install_seconds",
        "barrier_rtt_seconds",
    )

    def attach(self) -> None:
        """Connect the southbound fabric and replay discovery."""
        self.southbound.connect(self.bus)

    def telemetry(self) -> dict:
        """One snapshot of the control-plane telemetry: the process-wide
        metrics registry (counters/gauges/histograms, the jit-trace
        family) plus the oracle wall-time summary and the latest
        congestion-analytics report. The RPC mirror broadcasts exactly
        this dict as ``update_telemetry`` and the Prometheus exposition
        (api/telemetry.py) renders exactly this dict — one registry,
        two encodings, no chance of drift."""
        from sdnmpi_tpu.control import events as ev
        from sdnmpi_tpu.api.telemetry import telemetry_snapshot

        # the event log's own figures (event_log_events_total,
        # event_log_rotations_total) already live in the registry —
        # no hand-injected duplicates to reconcile
        snap = telemetry_snapshot()
        try:
            report = self.bus.request(ev.CongestionReportRequest()).report
        except LookupError:  # duck-typed minimal stacks
            report = {}
        if report:
            snap["congestion"] = report
        return snap

    # -- flight recorder seams (ISSUE 7) -----------------------------------

    def _span_tree(self, req) -> "object":
        from sdnmpi_tpu.control import events as ev

        tree = (
            self.flight.tree_for(req.span_id)
            if self.flight is not None
            else None
        )
        return ev.SpanTreeReply(tree)

    def _flight_dump(self, req) -> "object":
        from sdnmpi_tpu.control import events as ev

        bundle = (
            self.flight.freeze("manual", {})
            if self.flight is not None
            else {}
        )
        return ev.FlightDumpReply(bundle)

    def _timeline(self, req) -> "object":
        from sdnmpi_tpu.control import events as ev

        timeline = (
            self.timeline.series(req.names)
            if self.timeline is not None
            else {"series": {}, "n_rows": 0, "span_s": 0.0}
        )
        return ev.TimelineReply(timeline)

    def _traffic_tick(self) -> None:
        """Per-flush measured-traffic step: publish the matrix epoch the
        audit sweep just staged, then let the sentinel re-score against
        it (runs after audit.sweep by subscription order, before the
        flight recorder's trigger pass)."""
        self.traffic.flush()
        self.sentinel.sweep()

    def _traffic_matrix(self, req) -> "object":
        from sdnmpi_tpu.control import events as ev

        matrix = (
            self.traffic.matrix()
            if self.traffic is not None
            else {"epoch": 0, "mode": "off", "endpoints": [], "cells": []}
        )
        return ev.TrafficMatrixReply(matrix)

    def _replica_status(self, req) -> "object":
        from sdnmpi_tpu.control import events as ev

        status = (
            self.replica.status()
            if self.replica is not None
            else {"mode": "off"}
        )
        return ev.ReplicaStatusReply(status)

    def _publish_anomaly(self, bundle: dict) -> None:
        """Flight-recorder anomaly hook -> one EventAnomaly on the bus
        (the RPC mirror broadcasts it). The summary strips the bulky
        members — trees and full snapshots stay in the recorder and the
        dump file."""
        from sdnmpi_tpu.control import events as ev

        summary = {
            k: v
            for k, v in bundle.items()
            if k not in ("span_trees", "metrics", "events_tail", "exemplars")
        }
        if self.profile_capture is not None:
            # anomaly-armed device profiling (ISSUE 14): the capture
            # window opens the moment the trigger fires and closes on a
            # later flush tick — the profile OF the incident
            self.profile_capture.on_anomaly(bundle)
        self.bus.publish(ev.EventAnomaly(
            bundle["trigger"], summary, bundle.get("path")
        ))

    def _topology_forensics(self) -> dict:
        """Flight-bundle context: TopologyDB epoch/dirty-set state, the
        utilization plane's epoch, and the latest congestion report —
        the 'what did the graph look like' half of an incident."""
        db = self.topology_manager.topologydb
        plane = self.topology_manager.util_plane
        out = {
            "version": getattr(db, "version", None),
            "n_switches": len(getattr(db, "switches", ())),
            "util_epoch": plane.epoch if plane is not None else 0,
            "congestion": self.topology_manager.congestion,
        }
        log = getattr(db, "_delta_log", None)
        if log:
            out["delta_log_tail"] = [list(e) for e in list(log)[-16:]]
        return out
