"""Failure-domain recovery plane — desired state, acked installs, retries.

PRs 1-4 made the route->install pipeline fast; every leg of it was still
fire-and-forget (ISSUE 5): ``OFSouthbound._send`` returns a
queued/dropped verdict but a dropped window was simply lost, a switch
that crashed and redialed came back with an EMPTY flow table while the
Router still believed its flows were installed, and a half-open TCP
peer stayed "connected" forever. This module holds the bookkeeping that
closes the loop; the Router drives it (control/router.py) and the
southbounds feed it verdicts:

- :class:`DesiredFlowStore` — per-switch record of what SHOULD be
  installed (the Router's flow bookkeeping, minus the dedup role the
  SwitchFDB keeps). It survives ``EventDatapathDown``, which is the
  whole point: on ``EventDatapathUp`` for a known dpid the Router
  reconciles the returning switch against it, and the periodic
  anti-entropy pass re-drives switches whose window sends were dropped.
- :class:`InstallVerdict` — what one batched southbound send actually
  did: which switches got their whole byte span queued, which dropped
  it, and the OFPT_BARRIER_REQUEST xids terminating each switch's span
  (protocol/ofwire.py; the ack is the install's end-to-end receipt).
- :class:`RecoveryPlane` — pending-barrier table (ack -> RTT histogram,
  no ack -> resync), and the bounded per-switch retry queue with
  exponential backoff + seeded jitter (``Config.install_retry_max``,
  ``Config.install_retry_backoff_s``). Exhausted retries escalate to a
  full datapath resync (wipe + re-drive) rather than silently diverge.

DeltaPath (PAPERS.md) frames failure recovery as incremental repair;
this is the control-plane twin of that idea: recovery re-drives only
the failed switch's desired set through the PR-3 batched window path,
never the whole fabric.
"""

from __future__ import annotations

import dataclasses
import random
import time

from sdnmpi_tpu.utils.metrics import LATENCY_BUCKETS_S, REGISTRY

# -- recovery telemetry (first-class citizens of the PR-4 registry) -------
_m_reconcile_flows = REGISTRY.counter(
    "reconcile_flows_total",
    "desired flows re-driven to a switch by the reconciler",
)
_m_reconcile_passes = REGISTRY.counter(
    "reconcile_passes_total",
    "per-switch reconciliation passes (datapath-up + anti-entropy)",
)
_m_retries = REGISTRY.counter(
    "install_retries_total",
    "retry-queue re-drives of dropped/un-acked install windows",
)
_m_giveups = REGISTRY.counter(
    "install_retry_giveups_total",
    "switches whose bounded retries exhausted (escalated to resync)",
)
_m_resyncs = REGISTRY.counter(
    "install_resyncs_total",
    "full datapath resyncs (table wipe + state re-drive) after retry "
    "exhaustion",
)
_m_reconcile_deferred = REGISTRY.counter(
    "reconcile_deferred_total",
    "datapath-up reconciles deferred past the per-flush cap "
    "(Config.reconcile_max_per_flush — a power-cycled pod redialing at "
    "once must not flood the install plane)",
)
_m_barrier_rtt = REGISTRY.histogram(
    "barrier_rtt_seconds", LATENCY_BUCKETS_S,
    "install window send -> OFPT_BARRIER_REPLY round-trip",
)
_m_barrier_timeouts = REGISTRY.counter(
    "barrier_timeouts_total",
    "install windows whose barrier ack never arrived in time",
)
_m_pending_barriers = REGISTRY.gauge(
    "barriers_pending", "install windows awaiting their barrier ack"
)
_m_desired_flows = REGISTRY.gauge(
    "desired_flows", "flows in the desired-state store across all switches"
)
# registered here (not only in control/southbound.py, whose incrementing
# site lives beside the echo keepalive) so the family is present in
# every controller's exposition — a sim-fabric deployment's dashboards
# must not change shape when it moves to real TCP switches
REGISTRY.counter(
    "echo_timeouts_total",
    "half-open datapaths aborted by the controller-side echo keepalive",
)

#: early barrier acks kept for matching (the simulated Fabric acks
#: synchronously, BEFORE the caller can register the pending barrier)
_EARLY_ACK_CAP = 1024


@dataclasses.dataclass(frozen=True)
class FlowSpec:
    """What one desired flow installs beyond its (src, dst) match: the
    output port and the optional last-hop dl_dst rewrite (MPI virtual ->
    real MAC). Priority/timeouts are uniform per Config, so the store
    does not repeat them per row. ``collective`` marks rows installed by
    the phase scheduler's block plane (ISSUE 8): they reconcile like any
    other desired row but carry no SwitchFDB bookkeeping — the
    collective table, not the FDB, owns their lifecycle."""

    out_port: int
    rewrite: str | None = None
    collective: bool = False


@dataclasses.dataclass
class InstallVerdict:
    """Outcome of one batched southbound send (see module docstring).

    ``sent``/``dropped`` are dpids; a dpid appears in exactly one of
    them per send. ``barriers`` is ``[(dpid, xid), ...]`` — one
    OFPT_BARRIER_REQUEST terminates each successfully queued span when
    barriers are enabled, and its ack (EventBarrierAck) is the
    end-to-end receipt the RecoveryPlane times out on."""

    sent: list = dataclasses.field(default_factory=list)
    dropped: list = dataclasses.field(default_factory=list)
    barriers: list = dataclasses.field(default_factory=list)


class DesiredFlowStore:
    """dpid -> (src, dst) -> FlowSpec: what SHOULD be installed.

    Deliberately NOT cleared on datapath down — a crashed switch's
    desired set is exactly what the reconciler re-drives when it
    redials. Rows leave only through intentional teardown (revalidation
    re-routes, rank exits, switch-side expiry)."""

    def __init__(self) -> None:
        self.flows: dict[int, dict[tuple[str, str], FlowSpec]] = {}
        self._count = 0
        #: replication seam (ISSUE 20): when set, every effective
        #: mutation is reported as one op tuple —
        #: ``("record", dpid, src, dst, out_port, rewrite, collective)``
        #: with the values actually STORED (first-writer-wins ownership
        #: included), or ``("remove", dpid, src, dst)`` for a row that
        #: existed. None (the default) costs one attribute load per
        #: mutation — the single-controller path is unchanged.
        self.on_mutate = None

    def record(
        self, dpid: int, src: str, dst: str, out_port: int,
        rewrite: str | None = None, collective: bool = False,
    ) -> None:
        table = self.flows.setdefault(dpid, {})
        prev = table.get((src, dst))
        if prev is None:
            self._count += 1
        # ownership is first-writer-wins (cleared only by remove): a
        # re-record of the same match never flips a row between
        # FDB-owned and collective-owned — a reactive packet-in racing
        # a phased program's byte-identical row would otherwise hand it
        # flow timeouts on the next reconcile (and the reverse would
        # strip the FDB bookkeeping)
        spec = FlowSpec(
            int(out_port), rewrite,
            collective if prev is None else prev.collective,
        )
        table[(src, dst)] = spec
        _m_desired_flows.set(self._count)
        if self.on_mutate is not None:
            self.on_mutate((
                "record", dpid, src, dst, spec.out_port, spec.rewrite,
                spec.collective,
            ))

    def record_many(
        self, dpids, srcs, dsts, out_ports, rewrites,
        collective: bool = False,
    ) -> None:
        """Bulk :meth:`record` over parallel row sequences: one pass,
        one gauge update. The phase scheduler's install leg records a
        whole phase's rows (flagship scale: ~1e6 per program) here
        instead of a scalar call per row."""
        flows = self.flows
        on_mutate = self.on_mutate
        fresh = 0
        for dpid, src, dst, port, rewrite in zip(
            dpids, srcs, dsts, out_ports, rewrites
        ):
            table = flows.setdefault(dpid, {})
            prev = table.get((src, dst))
            if prev is None:
                fresh += 1
            # first-writer-wins ownership, same rule as record(): a
            # reactive flow can be byte-identical to a phase row (the
            # kickoff packet's), and stealing it would strip its
            # SwitchFDB bookkeeping on the next reconcile
            spec = FlowSpec(
                int(port), rewrite,
                collective if prev is None else prev.collective,
            )
            table[(src, dst)] = spec
            if on_mutate is not None:
                on_mutate((
                    "record", int(dpid), src, dst, spec.out_port,
                    spec.rewrite, spec.collective,
                ))
        self._count += fresh
        _m_desired_flows.set(self._count)

    def remove(self, dpid: int, src: str, dst: str) -> None:
        table = self.flows.get(dpid)
        if table is None or table.pop((src, dst), None) is None:
            return
        self._count -= 1
        if not table:
            del self.flows[dpid]
        _m_desired_flows.set(self._count)
        if self.on_mutate is not None:
            self.on_mutate(("remove", int(dpid), src, dst))

    def has(self, dpid: int, src: str, dst: str) -> bool:
        return (src, dst) in self.flows.get(dpid, {})

    def entries_for(self, dpid: int) -> list[tuple[str, str, FlowSpec]]:
        """This switch's desired rows in deterministic order (the
        reconciler's unit of work; sorted so a reconcile install is
        byte-identical run to run)."""
        table = self.flows.get(dpid, {})
        return [(s, d, spec) for (s, d), spec in sorted(table.items())]

    def total(self) -> int:
        return self._count


@dataclasses.dataclass
class _Retry:
    """One switch's pending re-drive: ``resync`` re-pushes the whole
    desired set; ``deletes`` re-drives specific dropped teardowns."""

    due: float = 0.0
    resync: bool = False
    deletes: set = dataclasses.field(default_factory=set)


class RecoveryPlane:
    """Retry/backoff + barrier-ack bookkeeping (see module docstring).

    Single-threaded by bus discipline, like every control-plane store.
    ``now`` parameters take ``time.monotonic()`` values; tests inject
    their own clock."""

    def __init__(self, config, seed: int = 0) -> None:
        self.config = config
        self.desired = DesiredFlowStore()
        self._rng = random.Random(seed)
        self._retries: dict[int, _Retry] = {}
        #: (dpid, xid) -> (send time, delete rows | None) of barriers
        #: awaiting their ack — DELETE windows carry their rows so an
        #: expiry re-drives the teardown itself, not just the ADD set
        self._pending: dict[tuple[int, int], tuple] = {}
        #: dpid -> teardown rows whose delivery is unconfirmed and whose
        #: switch went away before the retry could run. Survives the
        #: down edge on purpose: a TCP-bounced switch KEEPS its flow
        #: table, so reconcile-on-up must re-drive these deletes or the
        #: stale flows forward forever (the desired store alone only
        #: covers the ADD side).
        self._lost_deletes: dict[int, set] = {}
        #: acks that arrived before their send registered (sim fabrics
        #: ack synchronously inside flow_mods_window): (dpid, xid) -> t
        self._early_acks: dict[tuple[int, int], float] = {}
        #: consecutive failed re-drives per dpid (cleared on success)
        self._attempts: dict[int, int] = {}
        #: escalation hook fired when a dropped send cannot be queued
        #: because the dpid's bounded retries are already exhausted —
        #: the Router points this at its wipe-and-resync. Without it a
        #: drop landing AFTER exhaustion would be given up silently
        #: (found by the seeded chaos soak: a revalidation reinstall
        #: dropped post-exhaustion stayed missing through quiesce).
        self.on_exhausted = None

    # -- send outcomes ----------------------------------------------------

    def note_send(
        self, verdict, delete_rows=None, now: float | None = None,
        reschedule: bool = True,
    ) -> None:
        """Digest one batched send's outcome: register its barriers and
        (when ``reschedule``) queue retries for its dropped switches.
        ``delete_rows`` maps dpid -> set[(src, dst)] for DELETE windows,
        so a dropped teardown re-drives as a teardown, not a resync.
        ``verdict`` may be None (duck-typed southbounds without the
        verdict contract) — a no-op, the fire-and-forget legacy."""
        if verdict is None:
            return
        now = time.monotonic() if now is None else now
        for dpid, xid in verdict.barriers:
            t_ack = self._early_acks.pop((dpid, xid), None)
            if t_ack is not None:
                _m_barrier_rtt.observe(max(0.0, t_ack - now))
            else:
                rows = None if delete_rows is None else delete_rows.get(dpid)
                self._pending[(dpid, xid)] = (
                    now, None if rows is None else frozenset(rows)
                )
        _m_pending_barriers.set(len(self._pending))
        if not reschedule:
            return
        for dpid in verdict.dropped:
            rows = None if delete_rows is None else delete_rows.get(dpid)
            if (
                not self.schedule(dpid, now, deletes=rows,
                                  resync=rows is None)
                and self.on_exhausted is not None
            ):
                self.on_exhausted(dpid, now)

    def ack(self, dpid: int, xid: int, now: float | None = None) -> None:
        """An OFPT_BARRIER_REPLY (EventBarrierAck) arrived."""
        now = time.monotonic() if now is None else now
        entry = self._pending.pop((dpid, xid), None)
        if entry is None:
            # sim fabrics ack before note_send registers the barrier;
            # park it for the imminent match (bounded, FIFO-evicted)
            self._early_acks[(dpid, xid)] = now
            while len(self._early_acks) > _EARLY_ACK_CAP:
                self._early_acks.pop(next(iter(self._early_acks)))
            return
        _m_barrier_rtt.observe(now - entry[0])
        _m_pending_barriers.set(len(self._pending))

    def expire_barriers(self, now: float, timeout_s: float) -> dict:
        """Pop every pending barrier older than ``timeout_s``. Returns
        ``{dpid: (delete_rows, resync)}``: an expired DELETE window
        re-drives its own rows; an expired install window (rows None)
        asks for a desired-set resync — both may be true when several
        windows expired together."""
        expired = [k for k, (t0, _rows) in self._pending.items()
                   if now - t0 >= timeout_s]
        stale: dict[int, tuple[set, bool]] = {}
        for key in expired:
            _t0, rows = self._pending.pop(key)
            _m_barrier_timeouts.inc()
            deletes, resync = stale.get(key[0], (set(), False))
            if rows is None:
                resync = True
            else:
                deletes = deletes | set(rows)
            stale[key[0]] = (deletes, resync)
        if expired:
            _m_pending_barriers.set(len(self._pending))
        return stale

    def stash_lost_deletes(self, dpid: int, rows) -> None:
        """Park teardown rows whose switch is unreachable; the next
        reconcile drains them (see _lost_deletes)."""
        if rows:
            self._lost_deletes.setdefault(dpid, set()).update(rows)

    def take_lost_deletes(self, dpid: int) -> set:
        return self._lost_deletes.pop(dpid, set())

    # -- retry queue ------------------------------------------------------

    def schedule(
        self, dpid: int, now: float, deletes=None, resync: bool = True,
    ) -> bool:
        """Queue a re-drive for ``dpid`` with exponential backoff +
        jitter. Returns False when the bounded retries are exhausted —
        the caller escalates to a full resync (and the attempt clock
        restarts)."""
        attempt = self._attempts.get(dpid, 0) + 1
        if attempt > self.config.install_retry_max:
            _m_giveups.inc()
            self._attempts.pop(dpid, None)
            self._retries.pop(dpid, None)
            return False
        self._attempts[dpid] = attempt
        retry = self._retries.setdefault(dpid, _Retry())
        if deletes:
            retry.deletes |= set(deletes)
        if resync:
            retry.resync = True
        backoff = self.config.install_retry_backoff_s * (2 ** (attempt - 1))
        retry.due = now + backoff + self.jitter(backoff)
        return True

    def jitter(self, base: float) -> float:
        """One seeded jitter draw over ``base`` seconds: uniform in
        ``[0, base / 4)``. The shared de-synchronizer (ISSUE 20
        satellite) — retry backoff, retry-exhaustion wipe-resyncs, and
        reconcile-on-adopt all draw from this one seeded stream, so
        simultaneous failures spread instead of thundering-herd the
        install plane, and a seeded test replays the exact schedule.
        ``base <= 0`` draws nothing and returns 0 (the FAST_RECOVERY /
        zero-backoff test path stays byte-identical)."""
        if base <= 0:
            return 0.0
        return base * 0.25 * self._rng.random()

    def pop_due(self, now: float) -> list[tuple[int, _Retry]]:
        """Remove and return every retry whose backoff elapsed. The
        attempt count stays on the books until :meth:`succeed` — a
        re-drive that fails again resumes the backoff curve where it
        left off."""
        due = [(d, r) for d, r in self._retries.items() if r.due <= now]
        for dpid, _ in due:
            del self._retries[dpid]
        return due

    def succeed(self, dpid: int) -> None:
        """A re-drive (or reconcile) for ``dpid`` went through cleanly:
        its failure streak is over."""
        self._attempts.pop(dpid, None)

    def forget(self, dpid: int) -> None:
        """Datapath down: its pending barriers will never ack and its
        queued retries are moot — reconcile-on-up re-drives from the
        desired store, which this deliberately does NOT touch.
        Unconfirmed TEARDOWN rows are parked in the lost-delete ledger
        instead of dropped: a bounced switch keeps its flow table, and
        only re-driving the deletes can clear the stale entries."""
        retry = self._retries.pop(dpid, None)
        if retry is not None:
            self.stash_lost_deletes(dpid, retry.deletes)
        self._attempts.pop(dpid, None)
        stale = [k for k in self._pending if k[0] == dpid]
        for key in stale:
            _t0, rows = self._pending.pop(key)
            if rows:
                self.stash_lost_deletes(dpid, rows)
        if stale:
            _m_pending_barriers.set(len(self._pending))

    def in_flight(self, dpid: int) -> bool:
        """True while this switch has recovery machinery mid-air —
        un-acked barriers, a queued retry, or parked lost deletes. The
        audit plane (control/audit.py) skips such switches: their
        installed-vs-desired gap is already being repaired, and
        flagging it as fabric divergence would double-drive the repair
        (and page on what is ordinary retry latency)."""
        return (
            dpid in self._retries
            or dpid in self._lost_deletes
            or any(k[0] == dpid for k in self._pending)
        )

    # -- metric seams (the Router counts through these so the counters
    # live beside the machinery they describe) ----------------------------

    @staticmethod
    def note_reconcile(n_flows: int) -> None:
        _m_reconcile_passes.inc()
        _m_reconcile_flows.inc(n_flows)

    @staticmethod
    def note_retry() -> None:
        _m_retries.inc()

    @staticmethod
    def note_resync() -> None:
        _m_resyncs.inc()

    @staticmethod
    def note_reconcile_deferred() -> None:
        _m_reconcile_deferred.inc()
