"""Simulated switch fabric — the southbound the reference never had tests for.

The reference drives real OpenFlow 1.0 switches and was integration-tested
only by hand against Mininet (SURVEY §4); its unit tests bypass the network
entirely. This module provides the missing layer: an in-process fabric of
switches with priority-ordered flow tables, links, hosts, and per-port
counters, speaking the message shapes in protocol/openflow.py. The apps
drive it exactly like the reference drives datapaths (FlowMod / PacketOut /
PortStats / packet-in), so the whole control plane is testable end to end —
announcement in, flows installed, packets forwarded, counters ticking.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

from sdnmpi_tpu.control.events import (
    EventBarrierAck,
    EventDatapathDown,
    EventDatapathUp,
    EventHostAdd,
    EventLinkAdd,
    EventLinkDelete,
    EventFlowRemoved,
    EventPacketIn,
    EventPortAdd,
    EventSwitchEnter,
    EventSwitchLeave,
    EventTopologyChanged,
)
from sdnmpi_tpu.control.recovery import InstallVerdict
from sdnmpi_tpu.core.topology_db import Host, Link, Port, Switch
from sdnmpi_tpu.protocol import openflow as of
from sdnmpi_tpu.utils.metrics import REGISTRY

log = logging.getLogger(__name__)

# wire-mode twin of the real southbound's batched-encode volume counter
# (registered idempotently — whichever module imports first wins the
# help string, the instrument is shared)
_m_encode_bytes = REGISTRY.counter(
    "southbound_encode_bytes_total",
    "bytes produced by batched FlowMod window encodes",
)

_MAX_HOPS = 64  # forwarding-loop guard for the simulation


@dataclasses.dataclass
class SimPort:
    port_no: int
    #: ("switch", dpid, port_no) | ("host", mac) | None
    peer: Optional[tuple] = None
    rx_packets: int = 0
    rx_bytes: int = 0
    tx_packets: int = 0
    tx_bytes: int = 0


@dataclasses.dataclass
class _FlowEntry:
    priority: int
    match: of.Match
    actions: tuple[of.Action, ...]
    seq: int  # insertion order tie-break
    # expiry state (0 timeouts = permanent, the reference's only mode)
    idle_timeout: int = 0
    hard_timeout: int = 0
    installed_at: float = 0.0
    last_hit: float = 0.0
    packet_count: int = 0
    byte_count: int = 0
    cookie: int = 0
    #: True for the per-lookup entries synthesized from the block table
    #: (they carry no expiry state and are not in flow_table)
    synthetic: bool = False
    #: fault-injection state (control/faults.py "freeze" mutation): the
    #: entry still matches and forwards but its counters stopped — the
    #: dead-counter-ASIC fault the audit plane's counter-dead diff
    #: exists to catch
    frozen: bool = False


class _BlockSetEntry:
    """One switch's share of a FlowBlockSet: the (sub-flow, hop) rows
    whose ``hop_dpid`` is this switch.

    Row arrays are views of the install-time partition (no copies). The
    (src, dst) -> (member, hop row) map is built lazily on first
    lookup, so only switches that actually field a data-plane packet
    pay for indexing; a later row overwrites an earlier one for the
    same member, which shortcuts revisit loops (see FlowBlockSet).
    """

    __slots__ = ("priority", "seq", "block", "sub_rows", "hop_rows", "_index")

    def __init__(self, priority: int, seq: int, block, sub_rows, hop_rows):
        self.priority = priority
        self.seq = seq
        self.block = block
        self.sub_rows = sub_rows  # [R] int64 sub-flow ids at this switch
        self.hop_rows = hop_rows  # [R] int64 hop index of each row
        self._index = None

    def member(self, src_key: int, dst_key: int):
        if self._index is None:
            import numpy as np

            b = self.block
            bounds = np.asarray(b.bounds)
            starts = bounds[self.sub_rows]
            reps = bounds[self.sub_rows + 1] - starts
            total = int(reps.sum())
            # member ids: concatenated aranges of each row's slice
            # (vectorized — a core switch's entry can cover millions of
            # member flows, so no Python-level per-member loop)
            m_ids = np.repeat(starts + reps - reps.cumsum(), reps) + np.arange(
                total
            )
            last = self.hop_rows == np.asarray(b.hop_len)[self.sub_rows] - 1
            ports = np.where(
                last, -1, np.asarray(b.hop_port)[self.sub_rows, self.hop_rows]
            )
            m_ports = np.repeat(ports, reps)
            src = np.asarray(b.src)[m_ids].tolist()
            dst = np.asarray(b.dst)[m_ids].tolist()
            self._index = dict(
                zip(zip(src, dst), zip(m_ids.tolist(), m_ports.tolist()))
            )
        return self._index.get((src_key, dst_key))

    def actions_for(self, hit) -> tuple[of.Action, ...]:
        from sdnmpi_tpu.utils.mac import int_to_mac

        member, port = hit
        b = self.block
        if port >= 0:  # transit hop
            return (of.ActionOutput(port),)
        out: tuple[of.Action, ...] = ()
        if b.rewrite is not None:
            out = (of.ActionSetDlDst(int_to_mac(int(b.rewrite[member]))),)
        return out + (of.ActionOutput(int(b.final_port[member])),)


class SimSwitch:
    def __init__(self, fabric: "Fabric", dpid: int) -> None:
        self.fabric = fabric
        self.dpid = dpid
        self.ports: dict[int, SimPort] = {}
        self.flow_table: list[_FlowEntry] = []
        #: match -> entries with that exact match (Match is frozen, so
        #: hashable): O(1) ADD-replace and DELETE lookups instead of a
        #: full-table dataclass-eq scan per FlowMod — reconciliation
        #: re-drives whole desired sets, so installs dominate the sim
        self._by_match: dict[of.Match, list[_FlowEntry]] = {}
        self.block_table: list[_BlockSetEntry] = []
        self.local_delivered: list[of.Packet] = []  # OFPP_LOCAL sink
        #: packets parked switch-side while the controller decides
        #: (real OF 1.0 switches buffer the frame and send the controller
        #: a buffer_id; reference packet-outs reuse it, router.py:111-118)
        self.buffers: dict[int, of.Packet] = {}
        self._next_buffer = 0
        self._seq = 0

    MAX_BUFFERS = 1024  # FIFO cap, like a real switch's finite buffer pool

    def buffer_packet(self, pkt: of.Packet) -> int:
        self._next_buffer += 1
        self.buffers[self._next_buffer] = pkt
        while len(self.buffers) > self.MAX_BUFFERS:
            self.buffers.pop(next(iter(self.buffers)))
        return self._next_buffer

    def port(self, port_no: int) -> SimPort:
        return self.ports.setdefault(port_no, SimPort(port_no))

    # -- flow table -------------------------------------------------------

    def flow_mod(self, mod: of.FlowMod) -> None:
        if mod.command == of.OFPFC_ADD:
            # OF 1.0 §4.6: an ADD whose match+priority equal an existing
            # entry REPLACES it (counters reset). This is what makes
            # reconciliation idempotent: the recovery plane can re-drive
            # a desired set over a half-installed switch without
            # accumulating duplicate entries.
            bucket = self._by_match.setdefault(mod.match, [])
            old = next(
                (e for e in bucket if e.priority == mod.priority), None
            )
            if old is not None:
                bucket.remove(old)
                self.flow_table.remove(old)
            self._seq += 1
            now = self.fabric.now
            entry = _FlowEntry(
                mod.priority, mod.match, mod.actions, self._seq,
                idle_timeout=mod.idle_timeout,
                hard_timeout=mod.hard_timeout,
                installed_at=now, last_hit=now,
                cookie=mod.cookie,
            )
            bucket.append(entry)
            self.flow_table.append(entry)
            # highest priority first; earlier install wins ties
            self.flow_table.sort(key=lambda e: (-e.priority, e.seq))
        elif mod.command == of.OFPFC_DELETE:
            if mod.match == of.Match():
                # all-wildcard non-strict DELETE: the OF 1.0 "wipe the
                # table" idiom (every field wildcarded matches every
                # entry) — the recovery plane's resync escalation
                self.flow_table = []
                self._by_match.clear()
            else:
                doomed = self._by_match.pop(mod.match, None)
                if doomed:
                    doom_ids = {id(e) for e in doomed}
                    self.flow_table = [
                        e for e in self.flow_table if id(e) not in doom_ids
                    ]
        else:
            raise ValueError(f"unsupported flow_mod command {mod.command}")

    def drop_entries(self, doomed: set) -> None:
        """Remove entries (by identity) from the table AND the match
        index — the expiry sweep's bulk-removal seam (Fabric.tick)."""
        self.flow_table = [e for e in self.flow_table if id(e) not in doomed]
        for match in [
            m for m, b in self._by_match.items()
            if any(id(e) in doomed for e in b)
        ]:
            bucket = [e for e in self._by_match[match] if id(e) not in doomed]
            if bucket:
                self._by_match[match] = bucket
            else:
                del self._by_match[match]

    def add_block_entry(self, entry: _BlockSetEntry) -> None:
        self.block_table.append(entry)

    def remove_blocks(self, cookie: int) -> None:
        self.block_table = [
            e for e in self.block_table if e.block.cookie != cookie
        ]

    def lookup(self, pkt: of.Packet, in_port: int):
        """Highest-priority match across the scalar flow table and the
        block table (earlier install wins ties, like the scalar sort)."""
        best = None
        for entry in self.flow_table:
            if entry.match.matches(pkt, in_port):
                best = entry
                break  # table is priority-sorted
        if self.block_table:
            from sdnmpi_tpu.utils.mac import mac_to_int

            try:
                src_key = mac_to_int(pkt.eth_src)
                dst_key = mac_to_int(pkt.eth_dst)
            except ValueError:
                return best
            for b in self.block_table:
                if best is not None and (-best.priority, best.seq) <= (
                    -b.priority,
                    b.seq,
                ):
                    continue
                m = b.member(src_key, dst_key)
                if m is not None:
                    best = _FlowEntry(
                        b.priority, of.Match(), b.actions_for(m), b.seq,
                        synthetic=True,
                    )
        return best

    # -- data path --------------------------------------------------------

    def receive(self, pkt: of.Packet, in_port: int, hops: int) -> None:
        port = self.port(in_port)
        port.rx_packets += 1
        port.rx_bytes += _pkt_len(pkt)

        entry = self.lookup(pkt, in_port)
        if entry is not None and not entry.synthetic and not entry.frozen:
            # scalar-table hit: refresh the idle clock + counters (block
            # entries are synthesized per lookup and don't expire; a
            # fault-frozen entry forwards without counting)
            entry.last_hit = self.fabric.now
            entry.packet_count += 1
            entry.byte_count += _pkt_len(pkt)
        if entry is None:
            # table miss -> controller (the reference runs ryu-manager with
            # --noexplicit-drop so unmatched packets reach the apps,
            # run_router.sh:2); the frame is parked in the switch buffer
            # and its id rides the packet-in, as OF 1.0 switches do
            self.fabric.packet_in(
                self.dpid, in_port, pkt, self.buffer_packet(pkt)
            )
            return
        self.apply_actions(entry.actions, pkt, in_port, hops)

    def apply_actions(
        self,
        actions: tuple[of.Action, ...],
        pkt: of.Packet,
        in_port: int,
        hops: int,
    ) -> None:
        for action in actions:
            if isinstance(action, of.ActionSetDlDst):
                pkt = pkt.with_dst(action.mac)
            elif isinstance(action, of.ActionOutput):
                self._output(action.port, pkt, in_port, hops)
            else:
                raise ValueError(f"unsupported action {action!r}")
        # empty action list == drop (used by the IPv6-multicast drop rule,
        # reference: sdnmpi/topology.py:88-92)

    def _output(self, port_no: int, pkt: of.Packet, in_port: int, hops: int) -> None:
        if port_no == of.OFPP_CONTROLLER:
            self.fabric.packet_in(self.dpid, in_port, pkt, self.buffer_packet(pkt))
            return
        if port_no == of.OFPP_LOCAL:
            self.local_delivered.append(pkt)
            return
        if port_no == of.OFPP_IN_PORT:
            port_no = in_port
        port = self.ports.get(port_no)
        if port is None or port.peer is None:
            log.debug("dpid %s: output to dead port %s dropped", self.dpid, port_no)
            return
        port.tx_packets += 1
        port.tx_bytes += _pkt_len(pkt)
        self.fabric.transmit(port.peer, pkt, hops)

    def port_stats(self) -> list[of.PortStatsEntry]:
        return [
            of.PortStatsEntry(
                p.port_no, p.rx_packets, p.rx_bytes, p.tx_packets, p.tx_bytes
            )
            for p in sorted(self.ports.values(), key=lambda p: p.port_no)
        ]

    def flow_stats(self) -> list[of.FlowStatsEntry]:
        """The scalar flow table as OFPST_FLOW records — the audit
        plane's ground truth (ISSUE 15). Counters are the data-plane
        tallies the sim already keeps; block-table entries are NOT
        reported (they are this framework's array extension with no
        table rows a real OFPST_FLOW dump would carry — the collective
        table owns their lifecycle)."""
        now = self.fabric.now
        return [
            of.FlowStatsEntry(
                match=e.match, actions=e.actions, priority=e.priority,
                duration_sec=int(now - e.installed_at),
                idle_timeout=e.idle_timeout, hard_timeout=e.hard_timeout,
                cookie=e.cookie, packet_count=e.packet_count,
                byte_count=e.byte_count,
            )
            for e in self.flow_table
        ]

    def to_entity(self) -> Switch:
        return Switch.make(
            self.dpid, [Port(self.dpid, p.port_no) for p in self.ports.values()]
        )


class SimHost:
    def __init__(self, fabric: "Fabric", mac: str, dpid: int, port_no: int) -> None:
        self.fabric = fabric
        self.mac = mac
        self.dpid = dpid
        self.port_no = port_no
        self.received: list[of.Packet] = []

    def send(self, pkt: of.Packet) -> None:
        self.fabric.inject(self.dpid, pkt, self.port_no)

    def to_entity(self) -> Host:
        return Host(self.mac, Port(self.dpid, self.port_no))


class Fabric:
    """Container for the simulated network; owns discovery announcements.

    With ``wire=True`` every OpenFlow-shaped southbound exchange
    (FlowMod, PacketOut, PortStats, packet-in) round-trips through the
    byte-level OpenFlow 1.0 codec (protocol/ofwire.py) — the
    controller's messages are serialized to the real wire format and
    re-parsed before the switch acts on them, so the sim proves the
    same bytes a physical OF 1.0 switch would receive (reference emits
    these via Ryu, sdnmpi/router.py:49-62, monitor.py:54-60,
    process.py:61-79). ``flow_block_set`` is the one exception: the
    array-native collective install is this framework's extension with
    no OF 1.0 equivalent (see protocol/ofwire.py docstring)."""

    def __init__(self, wire: bool = False, discovery: str = "direct") -> None:
        if discovery not in ("direct", "packet"):
            raise ValueError(f"unknown discovery mode {discovery!r}")
        self.switches: dict[int, SimSwitch] = {}
        self.hosts: dict[str, SimHost] = {}
        self.links: list[tuple[int, int, int, int]] = []  # (a, pa, b, pb)
        self.bus = None  # set by connect()
        self.wire = wire
        #: called whenever an ingress burst fully drains (every host
        #: injection and its packet-in cascade has returned) and after
        #: each tick — the hook the Router's route coalescer flushes
        #: from, standing in for a real controller's event-loop idle
        #: callback. None = no coalescing.
        self.on_idle = None
        self._ingress_depth = 0
        #: "direct" publishes EventLinkAdd/EventHostAdd itself;
        #: "packet" announces only what a real OF channel would (datapath
        #: up + port sets) and leaves links/hosts for the controller's
        #: LLDP discovery app to learn from actual frames (the
        #: reference's --observe-links posture). Deletions stay
        #: event-driven either way: a real switch reports port-down /
        #: connection loss on the OF channel directly.
        self.discovery = discovery
        self._xid = 0
        #: simulation clock: advanced by tick(); stamps flow install /
        #: last-hit times for idle/hard expiry
        self.now: float = 0.0
        #: fault-injection schedule (control/faults.FaultPlan) consulted
        #: on every southbound send / stats pull; None = perfect fabric
        self.faults = None
        #: terminate each install span with a simulated barrier ack
        #: (Config.install_barriers; the Controller overrides this) —
        #: the sim's stand-in for OFPT_BARRIER_REQUEST/REPLY, through
        #: the byte codec when wire=True
        self.send_barriers: bool = True
        #: dpid -> cabled (host_mac, port_no) of a crashed switch,
        #: awaiting redial_switch (its links park in _dark_links)
        self._crashed: dict[int, list[tuple[str, int]]] = {}
        #: links whose restoration awaits BOTH endpoints redialing
        self._dark_links: set[tuple[int, int, int, int]] = set()
        #: dpid -> FIFO of deferred apply-thunks (a stalled TCP stream:
        #: bytes queued but not yet processed by the switch; everything
        #: behind the stall queues too, preserving per-connection order)
        self._stall_q: dict[int, list] = {}

    def _next_xid(self) -> int:
        self._xid += 1
        return self._xid

    # -- ingress bursts ----------------------------------------------------

    def inject(self, dpid: int, pkt: of.Packet, port_no: int) -> None:
        """Deliver a data-plane frame arriving at a switch port and,
        once the whole synchronous cascade (packet-ins, controller
        replies, forwarded copies) has drained, signal ``on_idle``.
        Nested deliveries (a controller packet-out re-entering the data
        plane mid-burst) do not re-signal: one burst, one idle edge."""
        self._ingress_depth += 1
        try:
            self.switches[dpid].receive(pkt, port_no, hops=0)
        finally:
            self._ingress_depth -= 1
            if self._ingress_depth == 0:
                self._notify_idle()

    def _notify_idle(self) -> None:
        if self.on_idle is not None:
            self.on_idle()

    # -- construction -----------------------------------------------------

    def add_switch(self, dpid: int) -> SimSwitch:
        sw = SimSwitch(self, dpid)
        self.switches[dpid] = sw
        if self.bus is not None:
            self.bus.publish(EventDatapathUp(dpid))
            self.bus.publish(EventSwitchEnter(sw.to_entity()))
        return sw

    def _port_added(self, dpid: int) -> None:
        """Announce a switch whose port set grew, so the controller's
        topology view tracks live ports (Ryu's port-add events play this
        role; TopologyDB.add_switch upserts by dpid). A dedicated event —
        not a replayed EventSwitchEnter — so the RPC mirror does not emit
        a redundant ``add_switch`` per cabling change."""
        if self.bus is not None:
            self.bus.publish(EventPortAdd(self.switches[dpid].to_entity()))

    def add_link(self, a: int, port_a: int, b: int, port_b: int) -> None:
        """Bidirectional link a:port_a <-> b:port_b (LLDP discovery reports
        both directed halves, as the reference's TopologyDB stores them)."""
        self.switches[a].port(port_a).peer = ("switch", b, port_b)
        self.switches[b].port(port_b).peer = ("switch", a, port_a)
        self.links.append((a, port_a, b, port_b))
        self._port_added(a)
        self._port_added(b)
        if self.bus is not None and self.discovery == "direct":
            for link in self._link_entities(a, port_a, b, port_b):
                self.bus.publish(EventLinkAdd(link))

    def add_host(self, mac: str, dpid: int, port_no: int) -> SimHost:
        host = SimHost(self, mac, dpid, port_no)
        self.hosts[mac] = host
        self.switches[dpid].port(port_no).peer = ("host", mac)
        self._port_added(dpid)
        if self.bus is not None and self.discovery == "direct":
            self.bus.publish(EventHostAdd(host.to_entity()))
        return host

    def add_silent_host(self, mac: str, dpid: int, port_no: int) -> SimHost:
        """A host cabled to a switch port that discovery has NOT seen
        (it has never sent a packet). The port exists on the switch —
        which is exactly why broadcasts must flood all non-inter-switch
        ports (reference: sdnmpi/topology.py:157-177), not just ports
        with discovered hosts: this host must still be reachable by the
        broadcast that would bootstrap it."""
        host = SimHost(self, mac, dpid, port_no)
        self.hosts[mac] = host
        self.switches[dpid].port(port_no).peer = ("host", mac)
        self._port_added(dpid)
        return host

    @staticmethod
    def _link_entities(a: int, pa: int, b: int, pb: int) -> tuple[Link, Link]:
        return (
            Link(Port(a, pa), Port(b, pb)),
            Link(Port(b, pb), Port(a, pa)),
        )

    # -- failure injection ------------------------------------------------

    def remove_link(self, a: int, port_a: int, b: int, port_b: int) -> None:
        self.links.remove((a, port_a, b, port_b))
        self.switches[a].port(port_a).peer = None
        self.switches[b].port(port_b).peer = None
        if self.bus is not None:
            for link in self._link_entities(a, port_a, b, port_b):
                self.bus.publish(EventLinkDelete(link))
            # one coalesced signal after both directed halves, so flow
            # revalidation runs once per topological change
            self.bus.publish(EventTopologyChanged())

    def crash_switch(self, dpid: int) -> None:
        """Kill a switch ungracefully: its OF session and links die and
        its flow state is LOST — :meth:`redial_switch` brings it back
        with an EMPTY table, exactly the scenario the recovery plane's
        desired-state reconciliation exists for. Unflushed stalled
        bytes die with the session; links are parked dark until both
        endpoints are back."""
        self._stall_q.pop(dpid, None)
        self._crashed[dpid] = [
            (mac, h.port_no) for mac, h in self.hosts.items()
            if h.dpid == dpid
        ]
        self._dark_links.update(
            l for l in self.links if dpid in (l[0], l[2])
        )
        self.remove_switch(dpid)

    def redial_switch(self, dpid: int) -> None:
        """A crashed switch reboots and redials: datapath-up + switch-
        enter fire for a switch with an EMPTY flow table (the Router
        still believed its flows were installed — PR 5's tentpole bug),
        its hosts re-peer, and every dark link with both endpoints live
        is restored."""
        hosts = self._crashed.pop(dpid)
        sw = self.add_switch(dpid)
        for mac, port_no in hosts:
            sw.port(port_no).peer = ("host", mac)
            self._port_added(dpid)
            if self.bus is not None and self.discovery == "direct":
                self.bus.publish(EventHostAdd(self.hosts[mac].to_entity()))
        for link in sorted(self._dark_links):
            a, pa, b, pb = link
            if a in self.switches and b in self.switches:
                self._dark_links.discard(link)
                self.add_link(a, pa, b, pb)
        if self.bus is not None:
            # one coalesced signal after the whole redial (links + hosts)
            # so flow revalidation runs once over the healed graph
            self.bus.publish(EventTopologyChanged())

    def release_stalls(self, dpid: int | None = None) -> None:
        """Flush stalled send streams: the queued bytes reach their
        switch now, in FIFO order (barrier acks included). ``None``
        releases every stalled stream (quiesce)."""
        dpids = [dpid] if dpid is not None else sorted(self._stall_q)
        for d in dpids:
            for thunk in self._stall_q.pop(d, []):
                thunk()

    def _stalled(self, dpid: int, fault: str | None) -> bool:
        """True when ``dpid``'s stream is (or just became) stalled —
        subsequent sends must queue behind it to preserve the
        per-connection FIFO a real TCP stream guarantees."""
        if dpid in self._stall_q:
            return True  # already stalled: everything queues behind
        if fault == "stall":
            self._stall_q[dpid] = []
            return True
        return False

    def remove_switch(self, dpid: int) -> None:
        sw = self.switches.pop(dpid)
        # datapath-down first so flow cleanup never targets the dead switch
        if self.bus is not None:
            self.bus.publish(EventDatapathDown(dpid))
        for a, pa, b, pb in [l for l in self.links if dpid in (l[0], l[2])]:
            self.links.remove((a, pa, b, pb))
            other, other_port = (b, pb) if a == dpid else (a, pa)
            if other in self.switches:
                self.switches[other].port(other_port).peer = None
            if self.bus is not None:
                for link in self._link_entities(a, pa, b, pb):
                    self.bus.publish(EventLinkDelete(link))
        if self.bus is not None:
            self.bus.publish(EventSwitchLeave(sw.to_entity()))
            self.bus.publish(EventTopologyChanged())

    # -- time / flow expiry ------------------------------------------------

    def tick(self, now: float) -> None:
        """Advance the simulation clock and expire timed-out flows.

        Real OF 1.0 switches age flows themselves and, because every
        install sets OFPFF_SEND_FLOW_REM (as the reference does,
        sdnmpi/router.py:61), report each expiry with ofp_flow_removed.
        The reference never handles that reply (SURVEY §2 defect); here
        the expiry is published as EventFlowRemoved — through the byte
        codec when wire=True — and the Router keeps the FDB coherent.
        """
        self.now = now
        for dpid, sw in sorted(self.switches.items()):
            expired: list[tuple[_FlowEntry, int]] = []
            for e in sw.flow_table:
                if e.hard_timeout > 0 and now - e.installed_at >= e.hard_timeout:
                    expired.append((e, 1))  # OFPRR_HARD_TIMEOUT
                elif e.idle_timeout > 0 and now - e.last_hit >= e.idle_timeout:
                    expired.append((e, 0))  # OFPRR_IDLE_TIMEOUT
            if not expired:
                continue
            doomed = {id(e) for e, _ in expired}
            sw.drop_entries(doomed)
            for e, reason in expired:
                self._flow_removed(dpid, e, reason)
        # time passed: any coalesced route lookups past their window
        # must not wait for the next data-plane burst
        self._notify_idle()

    def _flow_removed(self, dpid: int, e: _FlowEntry, reason: int) -> None:
        if self.bus is None:
            return
        match, priority = e.match, e.priority
        duration = self.now - e.installed_at
        packets, bytes_ = e.packet_count, e.byte_count
        if self.wire:
            from sdnmpi_tpu.protocol import ofwire

            rec = ofwire.decode_flow_removed(
                ofwire.encode_flow_removed(
                    match, priority, reason,
                    duration_sec=int(duration), idle_timeout=e.idle_timeout,
                    packet_count=packets, byte_count=bytes_,
                    xid=self._next_xid(),
                )
            )
            match, priority = rec["match"], rec["priority"]
            reason, duration = rec["reason"], rec["duration_sec"]
            packets, bytes_ = rec["packet_count"], rec["byte_count"]
        self.bus.publish(
            EventFlowRemoved(
                dpid, match, priority, reason,
                duration_sec=duration, packet_count=packets, byte_count=bytes_,
            )
        )

    # -- controller attachment --------------------------------------------

    def connect(self, bus) -> None:
        """Attach the control plane and replay discovery for the current
        network, the way Ryu's LLDP discovery populates a fresh controller
        (--observe-links, reference: run_router.sh:2)."""
        self.bus = bus
        for dpid, sw in sorted(self.switches.items()):
            bus.publish(EventDatapathUp(dpid))
            bus.publish(EventSwitchEnter(sw.to_entity()))
        if self.discovery != "direct":
            # links/hosts must be learned from frames (LLDP probes fired
            # by the discovery app's EventSwitchEnter handler + traffic)
            return
        for a, pa, b, pb in self.links:
            for link in self._link_entities(a, pa, b, pb):
                bus.publish(EventLinkAdd(link))
        for host in self.hosts.values():
            bus.publish(EventHostAdd(host.to_entity()))

    # -- southbound API used by the apps ----------------------------------

    def flow_mod(self, dpid: int, mod: of.FlowMod) -> bool:
        """Returns the queued/dropped verdict, mirroring
        OFSouthbound._send: False when the datapath is unknown or the
        fault plan dropped the bytes."""
        sw = self.switches.get(dpid)
        if sw is None:  # datapath died between event and flow_mod
            log.debug("flow_mod to unknown dpid %s dropped", dpid)
            return False
        fault = self.faults.send_fault(dpid) if self.faults else None
        if fault == "drop" or fault == "truncate":
            # a truncated scalar mod is simply lost (nothing partial to
            # apply at one-message granularity)
            return False
        if self.wire:
            from sdnmpi_tpu.protocol import ofwire

            mod = ofwire.decode_flow_mod(
                ofwire.encode_flow_mod(mod, xid=self._next_xid())
            )
        if self._stalled(dpid, fault):
            self._stall_q[dpid].append(lambda: sw.flow_mod(mod))
            return True  # queued (a stalled stream is not a drop)
        sw.flow_mod(mod)
        return True

    def flow_mods_batch(self, dpid: int, batch: of.FlowModBatch):
        """Per-switch FlowMod burst (see flow_mods_window)."""
        import numpy as np

        return self.flow_mods_window(
            np.full(len(batch), dpid, np.int64), batch
        )

    def _ack_barrier(self, dpid: int):
        """Simulate the barrier request/reply terminating one switch's
        span: returns ``(xid, publish_thunk | None)``. The thunk fires
        the EventBarrierAck (immediately for a live stream, deferred
        for a stalled one); None means the fault plan lost the reply —
        the request was still sent, so the caller records the pending
        barrier that will time out into an anti-entropy resync."""
        xid = self._next_xid()
        if self.wire:
            from sdnmpi_tpu.protocol import ofwire

            # round-trip request and reply through the byte codec, as
            # every other wire-mode exchange does
            xid = ofwire.decode_barrier_reply(
                ofwire.encode_barrier_reply(
                    ofwire.peek_header(
                        ofwire.encode_barrier_request(xid)
                    )[2]
                )
            )
        if self.faults is not None and self.faults.ack_fault(dpid):
            return xid, None  # install applied; the receipt was lost
        bus = self.bus
        return xid, (lambda: bus.publish(EventBarrierAck(dpid, xid))
                     if bus is not None else None)

    def flow_mods_window(self, dpids, batch: of.FlowModBatch) -> InstallVerdict:
        """A whole window's FlowMods across switches (``dpids`` is the
        [N] per-row switch id — the pipelined install plane's unit of
        transfer). With ``wire=True`` the window round-trips through
        ONE batched encode and the scalar per-message decoder over each
        row's byte span — proving the exact bytes a real switch would
        receive from OFSouthbound.flow_mods_window; otherwise the
        scalar twins apply directly. Unknown dpids are dropped like
        flow_mod's dead-datapath case.

        Returns the same :class:`InstallVerdict` contract as
        ``OFSouthbound.flow_mods_window`` — per-switch queued/dropped
        spans plus simulated barrier acks — with the fault plan
        injecting dropped/stalled/truncated spans and lost acks."""
        import numpy as np

        from sdnmpi_tpu.utils.arrays import group_spans

        dpids = np.asarray(dpids)
        verdict = InstallVerdict()
        if len(batch) == 0:
            return verdict
        blob = offsets = None
        if self.wire:
            from sdnmpi_tpu.protocol import ofwire

            blob, offsets = ofwire.encode_flow_mods_spans(
                batch, xid_base=self._xid + 1
            )
            self._xid += len(batch)
            # same instrument the real southbound records, so wire-mode
            # sims exercise the telemetry plane end to end
            _m_encode_bytes.inc(len(blob))
        mods = None if self.wire else list(batch.to_flow_mods())
        for lo, hi in group_spans(dpids):
            dpid = int(dpids[lo])
            sw = self.switches.get(dpid)
            if sw is None:
                log.debug("flow_mods_window span for unknown dpid dropped")
                verdict.dropped.append(dpid)
                continue
            fault = self.faults.send_fault(dpid) if self.faults else None
            if fault == "drop":
                verdict.dropped.append(dpid)
                continue
            end = hi
            if fault == "truncate":
                # the span's last TCP segment died mid-frame: the first
                # half of the messages applied, the tail is lost — the
                # partial-install case only the barrier/retry machinery
                # can detect and repair
                end = lo + max(0, (hi - lo) // 2)
            if self.wire:
                from sdnmpi_tpu.protocol import ofwire

                span_mods = [
                    ofwire.decode_flow_mod(
                        blob[int(offsets[i]) : int(offsets[i + 1])]
                    )
                    for i in range(lo, end)
                ]
            else:
                span_mods = mods[lo:end]
            if self._stalled(dpid, fault):
                q = self._stall_q[dpid]
                q.extend(
                    (lambda s=sw, m=m: s.flow_mod(m)) for m in span_mods
                )
                if fault == "truncate":
                    verdict.dropped.append(dpid)
                    continue
                if self.send_barriers:
                    xid, thunk = self._ack_barrier(dpid)
                    verdict.barriers.append((dpid, xid))
                    if thunk is not None:
                        q.append(thunk)  # the ack drains behind the span
                verdict.sent.append(dpid)
                continue
            for m in span_mods:
                sw.flow_mod(m)
            if fault == "truncate":
                verdict.dropped.append(dpid)
                continue
            if self.send_barriers:
                xid, thunk = self._ack_barrier(dpid)
                verdict.barriers.append((dpid, xid))
                if thunk is not None:
                    thunk()
            verdict.sent.append(dpid)
        return verdict

    def flow_block_set(self, block: of.FlowBlockSet) -> None:
        """Install a whole collective's flows: partition the (sub-flow,
        hop) rows by switch with array ops, then hand each switch ONE
        entry referencing its row slice — O(#switches) Python for
        S x L x M worth of flow entries. Unknown dpids are skipped like
        flow_mod's dead-datapath case."""
        import numpy as np

        hop_len = np.asarray(block.hop_len)
        s_count, l_max = np.asarray(block.hop_dpid).shape
        valid = np.arange(l_max)[None, :] < hop_len[:, None]
        sub_rows, hop_rows = np.nonzero(valid)
        dpids = np.asarray(block.hop_dpid)[sub_rows, hop_rows]
        if len(dpids) == 0:
            return
        order = np.argsort(dpids, kind="stable")
        dpids = dpids[order]
        sub_rows = sub_rows[order]
        hop_rows = hop_rows[order]
        cuts = np.flatnonzero(np.diff(dpids)) + 1
        starts = np.concatenate([[0], cuts])
        ends = np.concatenate([cuts, [len(dpids)]])
        for lo, hi in zip(starts, ends):
            sw = self.switches.get(int(dpids[lo]))
            if sw is None:
                log.debug("block rows for unknown dpid skipped")
                continue
            sw._seq += 1
            sw.add_block_entry(
                _BlockSetEntry(
                    block.priority, sw._seq, block,
                    sub_rows[lo:hi], hop_rows[lo:hi],
                )
            )

    def flow_blocks_delete(self, cookie: int) -> None:
        """Tear down every block entry of a collective install."""
        for sw in self.switches.values():
            sw.remove_blocks(cookie)

    def packet_out(self, dpid: int, out: of.PacketOut) -> None:
        sw = self.switches.get(dpid)
        if sw is None:  # datapath died between packet-in and reply
            log.debug("packet_out to unknown dpid %s dropped", dpid)
            return
        if self.wire:
            from sdnmpi_tpu.protocol import ofwire

            out = ofwire.decode_packet_out(
                ofwire.encode_packet_out(out, xid=self._next_xid())
            )
        pkt = out.data
        if out.buffer_id != of.OFP_NO_BUFFER:
            # use the switch-side buffered frame (reference:
            # sdnmpi/router.py:111-118); data, if any, is ignored
            pkt = sw.buffers.pop(out.buffer_id, None)
            if pkt is None:
                log.debug(
                    "packet_out for unknown buffer %s on dpid %s dropped",
                    out.buffer_id, dpid,
                )
                return
        sw.apply_actions(out.actions, pkt, out.in_port, hops=0)

    def port_stats(self, dpid: int) -> list[of.PortStatsEntry]:
        if self.faults is not None and self.faults.stats_fault(dpid):
            # delayed StatsReply: this pull returns nothing, exactly
            # like OFSouthbound.port_stats before the reply lands
            return []
        entries = self.switches[dpid].port_stats()
        if self.wire:
            from sdnmpi_tpu.protocol import ofwire

            entries = ofwire.decode_port_stats_reply(
                ofwire.encode_port_stats_reply(entries, xid=self._next_xid())
            )
        return entries

    def flow_stats(self, dpid: int):
        """Pull one switch's flow table (OFPST_FLOW, ISSUE 15). Returns
        None — NOT an empty list — when no reply is available (unknown
        datapath, or the fault plan delayed the StatsReply): the audit
        plane must never read "no answer" as "empty table", or a
        delayed reply would condemn every desired row as missing. With
        ``wire=True`` the reply round-trips the MULTIPART byte codec
        (encode splits on record boundaries, decode reassembles), so
        the sim proves the same part stream a real switch would send."""
        sw = self.switches.get(dpid)
        if sw is None:
            return None
        if self.faults is not None and self.faults.stats_fault(dpid):
            return None  # delayed StatsReply: nothing to serve this pull
        entries = sw.flow_stats()
        if self.wire:
            from sdnmpi_tpu.protocol import ofwire

            entries = ofwire.decode_flow_stats_reply(
                ofwire.encode_flow_stats_reply(
                    entries, xid=self._next_xid()
                )
            )
        return entries

    def connected_dpids(self) -> list[int]:
        return sorted(self.switches)

    # -- internal transit -------------------------------------------------

    def packet_in(
        self,
        dpid: int,
        in_port: int,
        pkt: of.Packet,
        buffer_id: int = of.OFP_NO_BUFFER,
    ) -> None:
        if self.bus is not None:
            if self.wire:
                from sdnmpi_tpu.protocol import ofwire

                pkt, in_port, buffer_id, _reason = ofwire.decode_packet_in(
                    ofwire.encode_packet_in(
                        pkt, in_port, buffer_id, xid=self._next_xid()
                    )
                )
            self.bus.publish(EventPacketIn(dpid, in_port, pkt, buffer_id))

    def transmit(self, peer: tuple, pkt: of.Packet, hops: int) -> None:
        if hops >= _MAX_HOPS:
            log.warning("dropping packet after %d hops (loop?)", hops)
            return
        if peer[0] == "host":
            host = self.hosts.get(peer[1])
            if host is not None:
                host.received.append(pkt)
        else:
            _, dpid, port_no = peer
            sw = self.switches.get(dpid)
            if sw is not None:
                sw.receive(pkt, port_no, hops + 1)


def _pkt_len(pkt: of.Packet) -> int:
    return 14 + len(pkt.payload)  # ethernet header + payload
