"""Real OpenFlow 1.0 TCP southbound — physical/OVS switches over bytes.

The reference inherited its transport from Ryu: switches dialed the
controller's TCP port, Ryu ran the version/features handshake, and the
apps saw datapath objects (reference: run_router.sh:2 `ryu-manager`;
every `datapath.send_msg` in sdnmpi/router.py:62, monitor.py:60,
process.py:79). This module is that transport, built directly on the
byte codec (protocol/ofwire.py):

- an asyncio TCP server on the standard OF port (6633);
- per connection: Hello + FeaturesRequest, then a framed read loop
  (``ofwire.peek_header`` lengths) dispatching Echo, FeaturesReply,
  PacketIn, FlowRemoved, and port StatsReply;
- the same app-facing surface as the simulated ``Fabric``
  (``flow_mod`` / ``packet_out`` / ``port_stats`` /
  ``flow_block_set`` / ``connected_dpids`` / the ``on_idle``
  burst-drained hook the route coalescer flushes from) and the same
  bus events (EventDatapathUp/Down, EventSwitchEnter/Leave,
  EventPacketIn, EventFlowRemoved) — so the entire controller,
  including ``Config.coalesce_routes``, runs unchanged against real
  switches; the Fabric remains the hermetic test double.

Asynchrony note: ``port_stats`` is a synchronous pull in the app API
(the Monitor differentiates counters at its own cadence). Over TCP it
returns the switch's most recent StatsReply and fires off a fresh
request — one sampling interval of lag, which the delta computation
absorbs (the first interval is a baseline anyway, reference:
sdnmpi/monitor.py:70-77).

``flow_block_set`` (this framework's array-native collective install,
no OF 1.0 equivalent) degrades to its per-row FlowMods on the wire.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from collections import deque

import numpy as np

from sdnmpi_tpu.control.events import (
    EventBarrierAck,
    EventDatapathDown,
    EventDatapathUp,
    EventFlowRemoved,
    EventPacketIn,
    EventPortAdd,
    EventPortDelete,
    EventSwitchEnter,
    EventSwitchLeave,
)
from sdnmpi_tpu.control.recovery import InstallVerdict
from sdnmpi_tpu.core.topology_db import Port, Switch
from sdnmpi_tpu.protocol import ofwire
from sdnmpi_tpu.protocol import openflow as of
from sdnmpi_tpu.utils.metrics import REGISTRY, SIZE_BUCKETS

log = logging.getLogger("OFSouthbound")

# queued/dropped verdicts of _send plus the batched-install wire volume
# (ISSUE 4): the registry the RPC telemetry feed and the text
# exposition both read.
_m_sends = REGISTRY.counter(
    "southbound_sends_total", "payloads queued to a datapath transport"
)
_m_drops = REGISTRY.counter(
    "southbound_drops_total",
    "payloads NOT queued (unknown peer or stalled-peer cut)",
)
_m_stall_cuts = REGISTRY.counter(
    "southbound_stall_cuts_total",
    "datapaths disconnected for exceeding the write-buffer cap",
)
_m_encode_bytes = REGISTRY.counter(
    "southbound_encode_bytes_total",
    "bytes produced by batched FlowMod window encodes",
)
_m_window_bytes = REGISTRY.histogram(
    "southbound_window_bytes", SIZE_BUCKETS,
    "batched encode size per FlowMod window",
)
_m_slices = REGISTRY.counter(
    "southbound_install_slices_total",
    "install_highwater byte slices written by batched installs",
)
_m_slice_wait = REGISTRY.histogram(
    "southbound_slice_wait_seconds",
    help="per-switch wait in the round-robin install scheduler between a "
    "slice being queued behind other switches' slices and its write "
    "(ISSUE 7: how long a switch's span sat parked while the window's "
    "other spans took their turns)",
)
_m_echo_timeouts = REGISTRY.counter(
    "echo_timeouts_total",
    "half-open datapaths aborted by the controller-side echo keepalive",
)
_m_stale_stats = REGISTRY.counter(
    "monitor_stale_stats_total",
    "stale cached port-stats state discarded when a datapath redialed",
)

OFP_TCP_PORT = 6633


class OFSouthbound:
    """OpenFlow 1.0 controller endpoint (see module docstring)."""

    def __init__(self, host: str = "0.0.0.0", port: int = OFP_TCP_PORT):
        self.host = host
        self.port = port
        self.bus = None
        self._server: asyncio.AbstractServer | None = None
        self._writers: dict[int, asyncio.StreamWriter] = {}
        self._ports: dict[int, set[int]] = {}
        self._stats: dict[int, list[of.PortStatsEntry]] = {}
        #: dpid -> last fully-assembled OFPST_FLOW reply (the audit
        #: plane's pull cache, same one-interval-lag contract as
        #: port_stats) and the in-flight multipart part list
        self._flow_stats: dict[int, list[of.FlowStatsEntry]] = {}
        self._flow_parts: dict[int, list[bytes]] = {}
        self._cookie_flows: dict[int, list] = {}
        self._xid = 0
        #: dpid -> (xid, sent_at monotonic) of the outstanding echo
        #: probe; a reply (any xid — liveness is liveness) clears it,
        #: echo_timeout without one aborts the transport so the reader
        #: loop exits and EventDatapathDown actually fires (the
        #: half-open-peer kill the recovery plane relies on)
        self._echo_pending: dict[int, tuple[int, float]] = {}
        #: controller-side keepalive knobs (Config.echo_interval_s /
        #: echo_timeout_s; the Controller overrides these)
        self.echo_interval: float = 15.0
        self.echo_timeout: float = 45.0
        #: terminate each batched install span with a BARRIER_REQUEST
        #: (Config.install_barriers; the Controller overrides this)
        self.send_barriers: bool = True
        #: called after a connection's read burst fully drains — every
        #: complete frame of one TCP read has been dispatched and no
        #: partial frame remains unhandled in this slice. The same idle
        #: edge the simulated Fabric provides (control/fabric.py), so
        #: the Router's route coalescer works on real switches too: a
        #: burst of packet-ins from one socket read resolves as one
        #: padded batched oracle call when the burst ends, and a lone
        #: parked packet never waits for a companion that isn't coming.
        #: None = no coalescing (Controller arms it).
        self.on_idle = None

    # -- lifecycle --------------------------------------------------------

    def connect(self, bus) -> None:
        """Bus attach; replay already-connected datapaths (none — real
        switches connect over TCP after serve())."""
        self.bus = bus

    async def serve(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        addr = self._server.sockets[0].getsockname()
        log.info("OpenFlow southbound listening on %s:%s", *addr[:2])

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for w in list(self._writers.values()):
            w.close()
        self._writers.clear()

    @property
    def bound_port(self) -> int:
        """The actual listening port (after serve(); for port=0 tests)."""
        return self._server.sockets[0].getsockname()[1]

    def _next_xid(self) -> int:
        self._xid += 1
        return self._xid

    # -- per-connection protocol ------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        dpid: int | None = None
        writer.write(ofwire.encode_hello(self._next_xid()))
        writer.write(ofwire.encode_features_request(self._next_xid()))
        await writer.drain()
        buf = b""
        try:
            while True:
                data = await reader.read(65536)
                if not data:
                    break
                buf += data
                dpid, buf = self._drain_frames(buf, dpid, writer)
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except (ValueError, struct.error) as e:
            # framing/version/truncation error: drop the switch
            log.warning("protocol error from dpid=%s: %s", dpid, e)
        finally:
            if dpid is not None and self._writers.get(dpid) is writer:
                del self._writers[dpid]
                self._ports.pop(dpid, None)
                self._stats.pop(dpid, None)
                self._flow_stats.pop(dpid, None)
                self._flow_parts.pop(dpid, None)
                self._echo_pending.pop(dpid, None)
                if self.bus is not None:
                    self.bus.publish(EventDatapathDown(dpid))
                    self.bus.publish(
                        EventSwitchLeave(Switch.make(dpid, []))
                    )
                log.info("datapath %#x disconnected", dpid)
            writer.close()

    def _drain_frames(self, buf: bytes, dpid: int | None,
                      writer: asyncio.StreamWriter):
        """Dispatch every complete frame in ``buf``; returns the
        (possibly learned) dpid and the remaining partial buffer.
        Replies are drained once per burst by the caller.

        The idle notification fires from a ``finally`` so a burst that
        dispatched SOME frames before a later frame raised (protocol
        error, dying socket) still flushes coalesced work — a parked
        route lookup has no timer here to rescue it otherwise."""
        progressed = False
        try:
            while len(buf) >= 8:
                # version-tolerant framing: a peer's HELLO advertises
                # its HIGHEST version (OVS default: 1.3+) and the
                # sides settle on the minimum — 1.0 here. Only a
                # non-HELLO at a version we never negotiated is a
                # protocol error.
                version, msg_type, length, xid = struct.unpack_from(
                    "!BBHI", buf
                )
                if version != ofwire.OFP_VERSION and (
                    msg_type != ofwire.OFPT_HELLO
                ):
                    raise ValueError(
                        f"message type {msg_type} at unnegotiated "
                        f"version 0x{version:02x}"
                    )
                if length < 8:
                    # OF header is 8 bytes; a shorter declared length
                    # would consume nothing and spin this loop forever
                    raise ValueError(f"bad header length {length}")
                if len(buf) < length:
                    break
                msg, buf = buf[:length], buf[length:]
                dpid = self._dispatch(msg_type, msg, xid, dpid, writer)
                progressed = True
        finally:
            if progressed:
                # burst drained: flush coalesced work (see on_idle)
                self._notify_idle()
        return dpid, buf

    def _notify_idle(self) -> None:
        if self.on_idle is not None:
            self.on_idle()

    def _dispatch(self, msg_type: int, msg: bytes, xid: int,
                  dpid: int | None, writer: asyncio.StreamWriter) -> int | None:
        """Handle one framed message; returns the (possibly learned) dpid."""
        if msg_type == ofwire.OFPT_HELLO:
            return dpid
        if msg_type == ofwire.OFPT_ECHO_REQUEST:
            writer.write(ofwire.encode_echo_reply(msg[8:], xid))
            return dpid
        if msg_type == ofwire.OFPT_ECHO_REPLY:
            # controller-side keepalive answered: the peer is live (any
            # reply proves it — no need to match the probe's xid)
            if dpid is not None:
                self._echo_pending.pop(dpid, None)
            return dpid
        if msg_type == ofwire.OFPT_FEATURES_REPLY:
            new_dpid, port_nos = ofwire.decode_features_reply(msg)
            stale = self._writers.get(new_dpid)
            if stale is not None and stale is not writer:
                # switch redialed before its old connection timed out:
                # abort the stale transport so its reader loop exits and
                # stops dispatching into this dpid's shared state (its
                # cleanup is a no-op — _writers already points here)
                log.warning(
                    "datapath %#x reconnected; aborting stale session",
                    new_dpid,
                )
                stale.transport.abort()
            # a redial is a NEW switch process: the previous
            # connection's cached StatsReply and outstanding echo probe
            # are stale. Without this, a dpid that disconnected between
            # Monitor passes and redialed before the next StatsReply
            # would serve the dead connection's counters (or, when its
            # down-path cleanup raced the redial, nothing) forever.
            if self._stats.pop(new_dpid, None) is not None:
                _m_stale_stats.inc()
            # same staleness rule for the flow-stats cache: a redialed
            # switch's table restarted (or at least its counters did)
            self._flow_stats.pop(new_dpid, None)
            self._flow_parts.pop(new_dpid, None)
            self._echo_pending.pop(new_dpid, None)
            self._writers[new_dpid] = writer
            self._ports[new_dpid] = set(port_nos)
            if self.bus is not None:
                self.bus.publish(EventDatapathUp(new_dpid))
                self.bus.publish(EventSwitchEnter(Switch.make(
                    new_dpid, [Port(new_dpid, p) for p in sorted(port_nos)]
                )))
            log.info("datapath %#x connected (%d ports)", new_dpid,
                     len(port_nos))
            return new_dpid
        if msg_type == ofwire.OFPT_ERROR:
            # before the dpid guard: a switch rejecting the handshake's
            # own FEATURES_REQUEST errors while dpid is still unknown.
            # Errors are diagnostics, not disconnects — even malformed
            # ones (a truncated body must not become newly fatal).
            who = f"{dpid:#x}" if dpid is not None else "(pre-handshake)"
            try:
                err_type, code, data = ofwire.decode_error(msg)
            except (ValueError, struct.error):
                log.warning(
                    "switch %s sent a malformed error message (%d bytes)",
                    who, len(msg),
                )
                return dpid
            log.warning(
                "switch %s rejected a request: xid=%d error type=%d "
                "code=%d (%d bytes of offending message)",
                who, xid, err_type, code, len(data),
            )
            return dpid
        if dpid is None:
            log.debug("pre-handshake message type %d ignored", msg_type)
            return dpid
        if msg_type == ofwire.OFPT_PORT_STATUS:
            reason, port_no, state = ofwire.decode_port_status(msg)
            ports = self._ports.setdefault(dpid, set())
            dead = reason == ofwire.OFPPR_DELETE or (
                reason == ofwire.OFPPR_MODIFY
                and state & ofwire.OFPPS_LINK_DOWN
            )
            if dead:
                ports.discard(port_no)
                if self.bus is not None:
                    # TopologyManager prunes the port's links AND drops
                    # it from the Switch entity (broadcast edge-port math)
                    self.bus.publish(EventPortDelete(dpid, port_no))
            elif port_no not in ports:
                # OFPPR_ADD, or a MODIFY back to link-up after a flap —
                # either way the port (re)joins the inventory and
                # EventPortAdd makes LLDP discovery reflood it
                ports.add(port_no)
                if self.bus is not None:
                    self.bus.publish(EventPortAdd(Switch.make(
                        dpid, [Port(dpid, p) for p in sorted(ports)]
                    )))
            return dpid
        if msg_type == ofwire.OFPT_PACKET_IN:
            pkt, in_port, buffer_id, _reason = ofwire.decode_packet_in(msg)
            if self.bus is not None:
                self.bus.publish(EventPacketIn(dpid, in_port, pkt, buffer_id))
        elif msg_type == ofwire.OFPT_FLOW_REMOVED:
            rec = ofwire.decode_flow_removed(msg)
            if self.bus is not None:
                self.bus.publish(EventFlowRemoved(
                    dpid, rec["match"], rec["priority"], rec["reason"],
                    float(rec["duration_sec"]), rec["packet_count"],
                    rec["byte_count"],
                ))
        elif msg_type == ofwire.OFPT_STATS_REPLY:
            stats_type, flags = ofwire.peek_stats_type(msg)
            if stats_type == ofwire.OFPST_FLOW:
                # MULTIPART: parts accumulate until REPLY_MORE clears,
                # then the whole table decodes in one batched pass —
                # a partial accumulation never serves as a table dump
                # (the audit would read the missing tail as divergence)
                parts = self._flow_parts.setdefault(dpid, [])
                parts.append(msg)
                if not flags & ofwire.OFPSF_REPLY_MORE:
                    del self._flow_parts[dpid]
                    self._flow_stats[dpid] = (
                        ofwire.decode_flow_stats_reply(parts)
                    )
            else:
                self._stats[dpid] = ofwire.decode_port_stats_reply(msg)
        elif msg_type == ofwire.OFPT_BARRIER_REPLY:
            # the end-to-end receipt of a batched install span: the
            # switch has processed everything sent before the barrier
            if self.bus is not None:
                self.bus.publish(EventBarrierAck(dpid, xid))
        else:
            log.debug("unhandled message type %d from %#x", msg_type, dpid)
        return dpid

    # -- southbound API used by the apps (Fabric-compatible) ---------------

    #: a switch that stops reading gets disconnected once this much
    #: unsent data accumulates, instead of buffering without bound —
    #: the same stalled-peer policy as the RPC mirror's backlog cap
    MAX_WRITE_BUFFER = 4 * 1024 * 1024

    def _send(self, dpid: int, payload: bytes) -> bool:
        """Write one payload toward a datapath; returns False when the
        bytes were NOT queued (unknown peer, or the stalled-peer cut
        fired) so synchronous burst loops can stop early — the reader
        task that prunes ``_writers`` cannot run mid-loop, so the
        return value is the only in-loop liveness signal."""
        w = self._writers.get(dpid)
        if w is None:  # datapath died between event and send
            log.debug("send to unknown dpid %s dropped", dpid)
            _m_drops.inc()
            return False
        if w.transport.get_write_buffer_size() > self.MAX_WRITE_BUFFER:
            log.warning(
                "datapath %#x stalled (%d bytes unsent); disconnecting",
                dpid, w.transport.get_write_buffer_size(),
            )
            # abort, not close: close() waits to flush a buffer the
            # stalled peer will never read, so connection_lost — and the
            # reader loop's datapath-down publication — would never fire
            w.transport.abort()
            _m_drops.inc()
            _m_stall_cuts.inc()
            return False
        w.write(payload)  # drained by the connection's event loop
        _m_sends.inc()
        return True

    def flow_mod(self, dpid: int, mod: of.FlowMod) -> bool:
        """Returns the queued/dropped send verdict (see _send) so
        callers with bookkeeping — the recovery plane, the block-install
        cookie record — never record a flow the wire never carried."""
        return self._send(
            dpid, ofwire.encode_flow_mod(mod, xid=self._next_xid())
        )

    # -- controller-side echo keepalive (ISSUE 5) --------------------------

    def echo_tick(self, now: float | None = None) -> None:
        """One keepalive pass: probe every connected datapath, abort any
        whose previous probe aged past ``echo_timeout``. A half-open
        peer (switch power-cut, NAT state loss, frozen middlebox)
        otherwise looks connected forever — no bytes flow, so the
        reader loop never errors, and EventDatapathDown never fires.
        The abort forces connection_lost, which runs the reader loop's
        full teardown path (datapath-down + switch-leave publication)."""
        import time as _time

        now = _time.monotonic() if now is None else now
        for dpid in list(self._writers):
            pending = self._echo_pending.get(dpid)
            if pending is not None:
                xid, t0 = pending
                if now - t0 >= self.echo_timeout:
                    log.warning(
                        "datapath %#x half-open: no echo reply in %.1fs; "
                        "disconnecting", dpid, now - t0,
                    )
                    _m_echo_timeouts.inc()
                    del self._echo_pending[dpid]
                    w = self._writers.get(dpid)
                    if w is not None:
                        w.transport.abort()
                continue  # probe still outstanding, not yet timed out
            xid = self._next_xid()
            if self._send(dpid, ofwire.encode_echo_request(b"", xid)):
                self._echo_pending[dpid] = (xid, now)

    async def run_echo(self) -> None:
        """Asyncio keepalive loop (armed by the launcher when
        ``Config.echo_interval_s`` > 0)."""
        while True:
            await asyncio.sleep(self.echo_interval)
            self.echo_tick()

    #: byte cap per batched-install write slice (Config.install_highwater;
    #: the Controller overrides this from its config). Slicing exists to
    #: re-arm the stalled-peer write-buffer check between slices: one
    #: giant burst cannot overshoot MAX_WRITE_BUFFER by more than a
    #: slice, and once the cut fires the rest of the burst is dropped
    #: instead of being pushed into the aborted transport.
    install_highwater: int = 256 * 1024

    def flow_mods_batch(self, dpid: int, batch: of.FlowModBatch):
        """Install a whole per-switch FlowMod burst: ONE batched wire
        encode (ofwire.encode_flow_mods_batch — numpy record assembly,
        no per-message struct.pack) flushed with writev-style sliced
        sends under the ``install_highwater`` backpressure cap. The
        bytes on the wire are identical to ``len(batch)`` flow_mod
        calls (asserted in tests/test_ofwire.py)."""
        return self.flow_mods_window(
            np.full(len(batch), dpid, np.int64), batch
        )

    def flow_mods_window(self, dpids, batch: of.FlowModBatch) -> InstallVerdict:
        """Install a whole *window's* FlowMods across switches: ``dpids``
        is the [N] per-row switch id, grouped (equal dpids contiguous —
        the Router's argsort guarantees it). The entire window is
        serialized in ONE batched encode; each switch receives its
        contiguous byte span of the blob (zero re-encoding per group),
        sliced under the ``install_highwater`` backpressure cap with the
        stalled-peer check re-armed between slices.

        Per-switch send scheduling (ISSUE 6, carried from PR 3): the
        slices of DIFFERENT switches interleave round-robin instead of
        each switch's whole span flushing before the next switch sees a
        byte — one slow or enormous span (a stalled peer grinding
        against the write-buffer cap, a hot switch with 100x the rows)
        no longer serializes the entire window behind it; every peer's
        first slice is queued within one round. Each switch's OWN byte
        stream is unchanged (its slices stay in order on its own
        transport), so the wire bytes per switch — and the
        scalar-equivalence the tests fuzz — are byte-identical.

        Returns an :class:`~sdnmpi_tpu.control.recovery.InstallVerdict`:
        which switches got their whole span queued (terminated by an
        OFPT_BARRIER_REQUEST when ``send_barriers`` — the ack is the
        install's receipt), and which dropped mid-span and need the
        recovery plane's retry queue. Fire-and-forget no more. Verdict
        order follows the window's group order regardless of which
        span's slices completed first."""
        dpids = np.asarray(dpids)
        verdict = InstallVerdict()
        n = len(batch)
        if n == 0:
            return verdict
        from sdnmpi_tpu.utils.arrays import group_spans

        blob, offsets = ofwire.encode_flow_mods_spans(
            batch, xid_base=self._xid + 1
        )
        self._xid += n
        _m_encode_bytes.inc(len(blob))
        _m_window_bytes.observe(len(blob))
        step = max(1, int(self.install_highwater))
        spans = [
            (int(dpids[lo]), blob[int(offsets[lo]) : int(offsets[hi])])
            for lo, hi in group_spans(dpids)
        ]
        sent_off = [0] * len(spans)
        #: group index -> ("sent" | "dropped", barrier xid | None)
        outcome: dict[int, tuple] = {}
        t_win = time.monotonic()
        ready = deque((i, t_win) for i in range(len(spans)))
        while ready:
            i, t_parked = ready.popleft()
            # per-switch slice wait (ISSUE 7): how long this switch's
            # next slice sat parked while other switches' slices took
            # their round-robin turns — the scheduler's fairness signal
            # (a stalled or enormous peer shows up HERE, not as other
            # switches' install latency)
            _m_slice_wait.observe(time.monotonic() - t_parked)
            dpid, span = spans[i]
            off = sent_off[i]
            if off < len(span):
                if not self._send(dpid, span[off : off + step]):
                    # peer unknown or cut for stalling: drop the rest
                    # of THIS switch's burst (other switches continue)
                    outcome[i] = ("dropped", None)
                    continue
                _m_slices.inc()
                sent_off[i] = off + step
                if sent_off[i] < len(span):
                    # back of the round-robin queue
                    ready.append((i, time.monotonic()))
                    continue
            # span fully queued: terminate it with the barrier NOW so
            # the receipt follows the last slice on this peer's stream
            if self.send_barriers:
                xid = self._next_xid()
                if not self._send(dpid, ofwire.encode_barrier_request(xid)):
                    # the span queued but its receipt cannot: treat the
                    # whole span as suspect (the transport just died)
                    outcome[i] = ("dropped", None)
                    continue
                outcome[i] = ("sent", xid)
            else:
                outcome[i] = ("sent", None)
        for i, (dpid, _) in enumerate(spans):
            state, xid = outcome[i]
            if state == "dropped":
                verdict.dropped.append(dpid)
                continue
            if xid is not None:
                verdict.barriers.append((dpid, xid))
            verdict.sent.append(dpid)
        return verdict

    def packet_out(self, dpid: int, out: of.PacketOut) -> None:
        self._send(dpid, ofwire.encode_packet_out(out, xid=self._next_xid()))

    def port_stats(self, dpid: int) -> list[of.PortStatsEntry]:
        """Last cached reply; kicks off the next request (one-interval
        lag — see module docstring)."""
        self._send(
            dpid, ofwire.encode_port_stats_request(xid=self._next_xid())
        )
        return self._stats.get(dpid, [])

    def flow_stats(self, dpid: int):
        """Last fully-assembled OFPST_FLOW table dump; kicks off the
        next request (one-interval lag, like port_stats). Returns None
        — not [] — before the first complete reply lands: the audit
        plane must never read "no answer yet" as "empty table"."""
        self._send(
            dpid, ofwire.encode_flow_stats_request(xid=self._next_xid())
        )
        return self._flow_stats.get(dpid)

    def invalidate_flow_stats(self, dpid: int) -> None:
        """Drop the cached table dump (and any in-flight multipart):
        the audit plane calls this when it KNOWS the table just changed
        out from under the cache (a wipe-and-resync) — the one-interval
        lag must not serve the pre-wipe dump as a post-wipe verify."""
        self._flow_stats.pop(dpid, None)
        self._flow_parts.pop(dpid, None)

    def connected_dpids(self) -> list[int]:
        return sorted(self._writers)

    def flow_block_set(self, block: of.FlowBlockSet) -> None:
        """Array-native collective install, expanded to one exact-match
        FlowMod per (member, hop) — the wire has no block equivalent.
        Installed matches are recorded per cookie so
        ``flow_blocks_delete`` can tear the collective down (OF 1.0 has
        no cookie-based delete; that arrived in 1.1)."""
        from sdnmpi_tpu.utils.mac import int_to_mac

        hop_dpid = np.asarray(block.hop_dpid)
        hop_port = np.asarray(block.hop_port)
        hop_len = np.asarray(block.hop_len)
        bounds = np.asarray(block.bounds)
        srcs = np.asarray(block.src)
        dsts = np.asarray(block.dst)
        final_port = np.asarray(block.final_port)
        rewrite = None if block.rewrite is None else np.asarray(block.rewrite)
        installed = self._cookie_flows.setdefault(block.cookie, [])
        for s in range(len(hop_len)):
            n_hops = int(hop_len[s])
            for m in range(int(bounds[s]), int(bounds[s + 1])):
                match = of.Match(
                    dl_src=int_to_mac(int(srcs[m])),
                    dl_dst=int_to_mac(int(dsts[m])),
                )
                for h in range(n_hops):
                    last = h == n_hops - 1
                    actions: tuple[of.Action, ...]
                    if last:
                        out = of.ActionOutput(int(final_port[m]))
                        actions = (
                            (of.ActionSetDlDst(int_to_mac(int(rewrite[m]))), out)
                            if rewrite is not None else (out,)
                        )
                    else:
                        actions = (of.ActionOutput(int(hop_port[s, h])),)
                    dpid = int(hop_dpid[s, h])
                    if self.flow_mod(dpid, of.FlowMod(
                        match, actions, block.priority, cookie=block.cookie,
                    )):
                        # record only flows the wire actually carried: a
                        # dropped send recorded here would make teardown
                        # delete flows that were never installed (and,
                        # worse, any identical match a later install DID
                        # put there)
                        installed.append((dpid, match, block.priority))

    def flow_blocks_delete(self, cookie: int) -> None:
        """Tear down a collective install: one OFPFC_DELETE per recorded
        exact match (see flow_block_set), the whole teardown serialized
        through ONE batched ``encode_flow_mods_spans`` window per
        priority (the same path as ``Router._del_flows_window`` — a
        large collective's teardown is a delete storm, and per-mod
        scalar encodes cost what the batched installs already
        eliminated). Byte-identical to the scalar per-mod loop modulo
        the xid sequence (differential-tested in tests/test_recovery.py)."""
        rows = self._cookie_flows.pop(cookie, [])
        if not rows:
            return
        from sdnmpi_tpu.utils.mac import macs_to_ints

        # one window per priority (priorities are uniform per block, but
        # a shared cookie across blocks must not cross-contaminate)
        by_prio: dict[int, list] = {}
        for dpid, match, priority in rows:
            by_prio.setdefault(priority, []).append((dpid, match))
        for priority, group in sorted(by_prio.items()):
            kd = np.array([d for d, _ in group], np.int64)
            order = np.argsort(kd, kind="stable")
            self.flow_mods_window(kd[order], of.FlowModBatch(
                src=macs_to_ints([m.dl_src for _, m in group])[order],
                dst=macs_to_ints([m.dl_dst for _, m in group])[order],
                out_port=np.zeros(len(group), np.int32),  # DELETE: no actions
                rewrite=None,
                priority=priority,
                command=of.OFPFC_DELETE,
                cookie=cookie,
            ))
