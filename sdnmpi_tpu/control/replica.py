"""Active/active controller pair: replicated stores, ownership fencing,
lease failover, reconcile-on-adopt (ISSUE 20).

Every serving number in the suite used to die with one process. This
module makes the control plane survive a controller loss by running N
(practically: two) controller processes over one fabric, split by the
deterministic switch partition of control/ownership.py:

- :class:`FencedSouthbound` wraps the shared southbound so a replica
  can only program the switches it owns. Fenced rows are counted and
  silently succeed (the owner installs them); owned ADD rows with a
  free cookie get stamped with the shard's ``(shard, epoch)`` token so
  the chaos acceptance can prove, from the fabric's own tables, which
  regime installed every row (no dual-owner installs).
- :class:`PairBus` is the event mux a shared fabric publishes into:
  dpid-scoped events go to the owning live replica (so ``Router.dps``
  *is* the ownership map, auto-scoping reconcile and the audit sweep);
  topology-wide events broadcast. Lifecycle events nobody owns (their
  owner is dead) are parked for the adopter.
- :class:`ReplicaPlane` replicates the three controller-private stores
  the fabric cannot re-teach quickly — desired-flow mutations (via the
  DesiredFlowStore ``on_mutate`` seam), process-registry events, and
  the TopologyDB delta-log version chain — as sequence-numbered op
  batches. A receive gap triggers a snapshot backfill over the same
  link (api/snapshot's capture), mirroring how the delta log itself
  falls back to full pulls. Lease heartbeats ride the same tick
  cadence as the PR-5 echo machinery (EventStatsFlush); when a peer's
  lease expires the survivor adopts its shards: epoch bump, replicated
  tail drained, then one ``EventDatapathUp`` republish per adopted
  switch — *jittered* (recovery.jitter) and rate-shaped by the
  router's existing ``reconcile_max_per_flush`` budget, audited by the
  PR-15 verify queue — so a failover storm cannot thundering-herd the
  fabric.

Replication transports: :class:`LoopLink` (in-process pair, the chaos
harness) and :class:`RpcReplicaLink` (JSON-RPC ``replica_relay``
notifications over the api/rpc WebSocket, the launch path). Messages
are JSON-safe dicts either way.

Everything here is opt-in: without ``--replica-peer`` no object in
this module is constructed and the single-controller path is
byte-identical (the acceptance pin).
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import time
from typing import Callable, Optional

import numpy as np

from sdnmpi_tpu.control import events as ev
from sdnmpi_tpu.control.ownership import OwnershipMap, cookie_token
from sdnmpi_tpu.control.recovery import InstallVerdict
from sdnmpi_tpu.protocol import openflow as of
from sdnmpi_tpu.utils.metrics import REGISTRY

log = logging.getLogger(__name__)

_m_ops_sent = REGISTRY.counter(
    "replica_ops_sent_total", "replicated store mutations shipped to the peer")
_m_ops_applied = REGISTRY.counter(
    "replica_ops_applied_total", "replicated store mutations applied from the peer")
_m_heartbeats = REGISTRY.counter(
    "replica_heartbeats_total", "lease heartbeats sent to the peer")
_m_seq_gaps = REGISTRY.counter(
    "replica_seq_gaps_total", "inbound replication sequence gaps detected")
_m_snapshot_backfills = REGISTRY.counter(
    "replica_snapshot_backfills_total",
    "full-state backfills applied after a replication gap")
_m_lease_expiries = REGISTRY.counter(
    "replica_lease_expiries_total", "peer leases declared expired")
_m_adoptions = REGISTRY.counter(
    "replica_adoptions_total", "shards adopted from a dead peer")
_m_fenced = REGISTRY.counter(
    "replica_fenced_rows_total",
    "FlowMod rows fenced off an unowned switch (the peer installs them)")
_m_lag = REGISTRY.gauge(
    "replication_lag",
    "op batches shipped but not yet acknowledged by the peer")
_m_epoch = REGISTRY.gauge(
    "ownership_epoch", "highest shard ownership epoch on this replica")


# -- transports ------------------------------------------------------------


class LoopLink:
    """In-memory replication pipe between two planes in one process —
    the chaos-acceptance transport. ``kill()`` models a controller
    death (its inbox drains to nowhere and its peer's sends drop);
    ``drop_next`` swallows the next N sends to force a sequence gap."""

    def __init__(self) -> None:
        self.inbox: collections.deque = collections.deque()
        self.peer: Optional["LoopLink"] = None
        self.alive = True
        self.dropped = 0
        self.drop_next = 0

    @classmethod
    def pair(cls) -> tuple["LoopLink", "LoopLink"]:
        a, b = cls(), cls()
        a.peer, b.peer = b, a
        return a, b

    def send(self, msg: dict) -> None:
        peer = self.peer
        if not self.alive or peer is None or not peer.alive:
            self.dropped += 1
            return
        if self.drop_next > 0:
            self.drop_next -= 1
            self.dropped += 1
            return
        peer.inbox.append(msg)

    def recv(self) -> list:
        out = list(self.inbox)
        self.inbox.clear()
        return out

    def kill(self) -> None:
        self.alive = False
        self.inbox.clear()


class RpcReplicaLink:
    """Launch-mode transport: outbound messages become JSON-RPC
    ``replica_relay`` notifications to the peer's api/rpc WebSocket
    (launch.py binds the sender once the client connects); inbound
    notifications are ingested by RPCInterface into :meth:`ingest`.
    Sends before the peer is reachable drop — the sequence gap they
    open is exactly what the snapshot backfill protocol repairs."""

    def __init__(self) -> None:
        self.inbox: collections.deque = collections.deque()
        self.dropped = 0
        self._send: Optional[Callable[[dict], None]] = None

    def bind_sender(self, fn: Callable[[dict], None]) -> None:
        self._send = fn

    def send(self, msg: dict) -> None:
        if self._send is None:
            self.dropped += 1
            return
        try:
            self._send(msg)
        except Exception:  # peer unreachable: gap now, backfill later
            self.dropped += 1

    def ingest(self, msg: dict) -> None:
        self.inbox.append(msg)

    def recv(self) -> list:
        out = list(self.inbox)
        self.inbox.clear()
        return out


# -- fenced southbound -----------------------------------------------------

#: fabric-global knobs the Controller pushes at construction; they must
#: land on the real southbound, not be shadowed on the proxy
_FORWARD_ATTRS = frozenset(
    {"install_highwater", "send_barriers", "echo_interval", "echo_timeout"}
)


def _slice_batch(batch: "of.FlowModBatch", keep: np.ndarray):
    return dataclasses.replace(
        batch,
        src=np.asarray(batch.src)[keep],
        dst=np.asarray(batch.dst)[keep],
        out_port=np.asarray(batch.out_port)[keep],
        rewrite=(
            None if batch.rewrite is None
            else np.asarray(batch.rewrite)[keep]
        ),
    )


class FencedSouthbound:
    """Ownership fence + epoch stamp in front of a (shared) southbound.

    Sends to unowned switches are counted and swallowed *as successes*
    (empty verdict / True): the owner replica installs those rows, so
    they must not look like drops to the caller's retry machinery.
    Owned ADD rows whose cookie is free (0) are stamped with the
    shard's current ``(shard, epoch)`` token; nonzero cookies (the
    block plane's collective identities) pass untouched. Everything
    else — stats, barriers, packet-out — delegates to the wrapped
    southbound, so ``hasattr`` feature probes see the fabric's real
    surface.

    ``shared=True`` (two controllers, one in-process fabric) keeps
    ``on_idle`` local — the pair harness composes both routers' flush
    callbacks — and refuses ``connect`` (the PairBus is connected
    once, not per controller)."""

    def __init__(self, southbound, ownership: OwnershipMap,
                 shared: bool = True) -> None:
        d = self.__dict__
        d["southbound"] = southbound
        d["ownership"] = ownership
        d["shared"] = shared
        d["on_idle"] = None

    def __getattr__(self, name):
        return getattr(self.__dict__["southbound"], name)

    def __setattr__(self, name, value) -> None:
        if name in _FORWARD_ATTRS:
            setattr(self.__dict__["southbound"], name, value)
            return
        if not self.__dict__["shared"] and name not in (
            "southbound", "ownership", "shared"
        ):
            # sole user of the southbound (launch mode): every write —
            # on_idle, fault plans, clocks — belongs on the real fabric
            setattr(self.__dict__["southbound"], name, value)
            if name != "on_idle":
                return
        self.__dict__[name] = value

    def connect(self, bus) -> None:
        if self.__dict__["shared"]:
            raise RuntimeError(
                "shared pair fabric: connect the PairBus once via "
                "ControllerPair.attach(), not per controller")
        self.__dict__["southbound"].connect(bus)

    # -- install plane, fenced --

    def flow_mod(self, dpid: int, mod: "of.FlowMod"):
        om = self.ownership
        if not om.owns(dpid):
            _m_fenced.inc()
            return True  # the owner installs it; not a send failure
        if mod.command == of.OFPFC_ADD and mod.cookie == 0:
            mod = dataclasses.replace(mod, cookie=om.cookie_token(dpid))
        return self.southbound.flow_mod(dpid, mod)

    def flow_mods_batch(self, dpid: int, batch: "of.FlowModBatch"):
        om = self.ownership
        if not om.owns(dpid):
            _m_fenced.inc(len(batch))
            return InstallVerdict()
        if batch.command == of.OFPFC_ADD and batch.cookie == 0:
            batch = dataclasses.replace(
                batch, cookie=om.cookie_token(dpid))
        return self.southbound.flow_mods_batch(dpid, batch)

    def flow_mods_window(self, dpids, batch: "of.FlowModBatch"):
        om = self.ownership
        dpids = np.asarray(dpids)
        # vectorized per-row token: shard is dpid % count, token 0 for
        # shards served elsewhere (= fenced rows)
        shard_tok = np.zeros(om.count, dtype=np.int64)
        for s in range(om.count):
            if om.assignment[s] == om.index:
                shard_tok[s] = cookie_token(s, om.epoch.get(s, 0))
        tokens = shard_tok[dpids % om.count]
        owned = tokens != 0
        n_fenced = int(len(dpids) - int(owned.sum()))
        if n_fenced:
            _m_fenced.inc(n_fenced)
            if not owned.any():
                return InstallVerdict()
        if batch.command != of.OFPFC_ADD or batch.cookie != 0:
            # deletes and pre-cookied (collective) bursts: fence only
            if not n_fenced:
                return self.southbound.flow_mods_window(dpids, batch)
            keep = np.flatnonzero(owned)
            return self.southbound.flow_mods_window(
                dpids[keep], _slice_batch(batch, keep))
        # a FlowModBatch carries ONE cookie but owned shards may sit at
        # different epochs: forward one sub-window per token value. The
        # token is a function of dpid, so the sub-windows partition the
        # dpid set (per-dpid spans stay contiguous, verdicts disjoint).
        verdict = InstallVerdict()
        for tok in np.unique(tokens[owned]):
            keep = np.flatnonzero(tokens == tok)
            sub = dataclasses.replace(
                _slice_batch(batch, keep), cookie=int(tok))
            v = self.southbound.flow_mods_window(dpids[keep], sub)
            verdict.sent += v.sent
            verdict.dropped += v.dropped
            verdict.barriers += v.barriers
        return verdict


# -- shared-fabric event mux ----------------------------------------------


class PairBus:
    """The bus a *shared* fabric publishes into when two controllers
    ride one fabric: dpid-scoped events route to the live replica that
    owns the switch, topology-wide events broadcast to every live
    replica. Lifecycle events whose owner is dead are parked
    (``unowned_live`` / ``unowned_down``) so the adopter can
    reconstruct exact switch liveness at failover — the in-process
    twin of the replicated tail."""

    def __init__(self) -> None:
        self.nodes: dict[int, tuple] = {}  # index -> (bus, ownership)
        self.dead: set[int] = set()
        self.unowned_live: set[int] = set()
        self.unowned_down: set[int] = set()

    def register(self, index: int, bus, ownership: OwnershipMap) -> None:
        self.nodes[index] = (bus, ownership)

    def kill(self, index: int) -> None:
        self.dead.add(index)

    def publish(self, event) -> None:
        dpid = getattr(event, "dpid", None)
        alive = [
            (i, b, o) for i, (b, o) in sorted(self.nodes.items())
            if i not in self.dead
        ]
        if dpid is None:
            for _i, b, _o in alive:
                b.publish(event)
            return
        owners = [b for _i, b, o in alive if o.owns(dpid)]
        if not owners:
            if isinstance(event, ev.EventDatapathUp):
                self.unowned_live.add(int(dpid))
                self.unowned_down.discard(int(dpid))
            elif isinstance(event, ev.EventDatapathDown):
                self.unowned_down.add(int(dpid))
                self.unowned_live.discard(int(dpid))
            return
        for b in owners:
            b.publish(event)

    def take_orphans(self) -> tuple[list[int], list[int]]:
        """Drain the parked lifecycle state: (came up, went down) since
        the owner died, consumed exactly once by the adopter."""
        live = sorted(self.unowned_live)
        down = sorted(self.unowned_down)
        self.unowned_live = set()
        self.unowned_down = set()
        return live, down


# -- the replica plane -----------------------------------------------------


class ReplicaPlane:
    """Store replication + lease failover for one replica of the pair.

    Ticks on the controller's EventStatsFlush edge (the same cadence
    the PR-5 echo keepalive rides). Each tick: drain inbound messages,
    ship the TopologyDB version chain and staged store ops as one
    sequence-numbered batch, heartbeat, check the peer's lease, drain
    jittered adoption republies and rate-capped targeted re-drives.

    The op log is *semantic*, not byte-oriented: desired-flow
    mutations replay through DesiredFlowStore.record/remove (with the
    ``_applying`` latch suppressing echo), registry events replay
    through the rankdb + a republish (so the peer's Router prunes
    flows for departed ranks on the switches *it* owns), topology
    deltas ship as version markers (content rides the broadcast
    discovery events; a gap falls back to the api/snapshot backfill,
    exactly like the delta log's own full-pull fallback)."""

    def __init__(self, controller, ownership: OwnershipMap, link,
                 config, clock: Callable[[], float] = time.monotonic,
                 mux: Optional[PairBus] = None) -> None:
        self.controller = controller
        self.ownership = ownership
        self.link = link
        self.config = config
        self.clock = clock
        self.mux = mux
        self.bus = controller.bus
        self.router = controller.router
        self.index = ownership.index

        self._applying = False   # replaying peer ops: don't re-stage
        self._staged: list = []
        self._seq_out = 0        # last batch shipped
        self._seq_in = 0         # last batch applied
        self._need_backfill = False
        self._topo_version = 0   # last TopologyDB version shipped
        self._peer_topo_version = 0
        self._peer_acked = 0
        self._peer_dps: dict[int, list[int]] = {}
        self._peer_alive: dict[int, bool] = {}
        self._last_heard: dict[int, float] = {}
        self._last_hb: Optional[float] = None
        self._adopt_due: list[tuple[float, int]] = []
        self._redrive_q: collections.deque = collections.deque()
        self._redrive_rows: dict[int, set] = {}
        self._delete_rows: dict[int, set] = {}

        self.router.recovery.desired.on_mutate = self._desired_mutated
        self.bus.subscribe(ev.EventProcessAdd, self._process_add)
        self.bus.subscribe(ev.EventProcessDelete, self._process_delete)
        _m_epoch.set(max(ownership.epoch.values(), default=0))

    # -- staging (local mutations -> op log) --

    def _desired_mutated(self, op: tuple) -> None:
        if not self._applying:
            self._staged.append(("desired",) + tuple(op))

    def _process_add(self, event: ev.EventProcessAdd) -> None:
        if not self._applying:
            self._staged.append(("rank", "add", int(event.rank), event.mac))

    def _process_delete(self, event: ev.EventProcessDelete) -> None:
        if not self._applying:
            self._staged.append(("rank", "del", int(event.rank)))

    # -- tick --

    def tick(self, now: Optional[float] = None) -> None:
        now = self.clock() if now is None else now
        for msg in self.link.recv():
            self._handle(msg, now)
        self._ship_topology()
        self._flush_ops()
        interval = self.config.replica_lease_interval_s
        if self._last_hb is None or now - self._last_hb >= interval:
            self._send_heartbeat(now)
        self._check_leases(now)
        self._drain_adoptions(now)
        self._drain_redrives()
        if any(self._peer_alive.get(p, True) for p in self._peers()):
            _m_lag.set(max(0, self._seq_out - self._peer_acked))
        else:
            _m_lag.set(0)

    def _peers(self) -> list[int]:
        return [i for i in range(self.ownership.count) if i != self.index]

    def _ship_topology(self) -> None:
        db = self.controller.topology_manager.topologydb
        version = db.version
        if version == self._topo_version:
            return
        deltas = db.deltas_since(self._topo_version)
        if deltas is None:
            # our own delta log no longer covers what the peer missed:
            # ship the full entity map, the log's own fallback shape
            self._staged.append(("topo_full", version, db.to_dict()))
        else:
            self._staged.append(
                ("topo", version, [list(e) for e in deltas]))
        self._topo_version = version

    def _flush_ops(self) -> None:
        if not self._staged:
            return
        self._seq_out += 1
        self.link.send({
            "kind": "ops", "from": self.index, "seq": self._seq_out,
            "ops": self._staged,
        })
        _m_ops_sent.inc(len(self._staged))
        self._staged = []

    def _send_heartbeat(self, now: float) -> None:
        self.link.send({
            "kind": "hb", "from": self.index,
            "seq": self._seq_out, "acked": self._seq_in,
            "dps": sorted(int(d) for d in self.router.dps),
            "ownership": self.ownership.to_dict(),
        })
        _m_heartbeats.inc()
        self._last_hb = now

    # -- inbound --

    def _handle(self, msg: dict, now: float) -> None:
        kind = msg.get("kind")
        if kind == "ops":
            self._handle_ops(msg)
        elif kind == "hb":
            frm = int(msg["from"])
            if not self._peer_alive.get(frm, True):
                # a declared-dead peer talking again: its shards were
                # adopted and its epoch fenced out — it must restart
                log.warning("replica %d: heartbeat from expired peer %d "
                            "(fenced; it must rejoin via restart)",
                            self.index, frm)
                return
            self._last_heard[frm] = now
            self._peer_acked = max(self._peer_acked, int(msg["acked"]))
            self._peer_dps[frm] = [int(d) for d in msg.get("dps", ())]
        elif kind == "snap_req":
            self._send_snapshot()
        elif kind == "snap":
            self._apply_snapshot(msg.get("snapshot") or {})
            self._seq_in = int(msg["seq"])
            self._need_backfill = False
            _m_snapshot_backfills.inc()

    def _handle_ops(self, msg: dict) -> None:
        seq = int(msg["seq"])
        if self._need_backfill or seq <= self._seq_in:
            return  # awaiting backfill / duplicate
        if seq != self._seq_in + 1:
            _m_seq_gaps.inc()
            self._need_backfill = True
            log.warning("replica %d: replication gap (have %d, got %d); "
                        "requesting snapshot backfill",
                        self.index, self._seq_in, seq)
            self.link.send({"kind": "snap_req", "from": self.index})
            return
        for op in msg.get("ops", ()):
            self._apply_op(tuple(op))
        self._seq_in = seq

    def _send_snapshot(self) -> None:
        from sdnmpi_tpu.api.snapshot import snapshot_controller

        self._flush_ops()  # the snapshot covers everything staged
        self.link.send({
            "kind": "snap", "from": self.index, "seq": self._seq_out,
            "snapshot": snapshot_controller(self.controller),
        })

    # -- op replay --

    def _apply_op(self, op: tuple) -> None:
        kind = op[0]
        if kind == "desired":
            self._apply_desired(op[1:])
        elif kind == "rank":
            self._apply_rank(op[1:])
        elif kind in ("topo", "topo_full"):
            self._peer_topo_version = int(op[1])
            if kind == "topo_full":
                pass  # entity content rides the broadcast discovery
                # events in-process; launch mode backfills via snapshot
        _m_ops_applied.inc()

    def _apply_desired(self, op: tuple) -> None:
        verb, dpid = op[0], int(op[1])
        desired = self.router.recovery.desired
        self._applying = True
        try:
            if verb == "record":
                _v, _d, src, dst, out_port, rewrite, collective = op
                desired.record(dpid, src, dst, int(out_port), rewrite,
                               bool(collective))
            else:
                _v, _d, src, dst = op
                desired.remove(dpid, src, dst)
        finally:
            self._applying = False
        if not self.ownership.owns(dpid):
            return
        # owned switch: the peer computed a route crossing our shard —
        # queue a targeted, rate-capped re-drive (or delete)
        if verb == "record":
            self._redrive_rows.setdefault(dpid, set()).add((src, dst))
            if dpid not in self._redrive_q:
                self._redrive_q.append(dpid)
        else:
            self._delete_rows.setdefault(dpid, set()).add((src, dst))
            if dpid not in self._redrive_q:
                self._redrive_q.append(dpid)

    def _apply_rank(self, op: tuple) -> None:
        pm = self.controller.process_manager
        self._applying = True
        try:
            if op[0] == "add":
                rank, mac = int(op[1]), op[2]
                pm.rankdb.add_process(rank, mac)
                # republish: our Router prunes/installs for this rank
                # on the switches WE own (the peer's sends are fenced)
                self.bus.publish(ev.EventProcessAdd(rank, mac))
            else:
                rank = int(op[1])
                pm.rankdb.delete_process(rank)
                self.bus.publish(ev.EventProcessDelete(rank))
        finally:
            self._applying = False

    def _apply_snapshot(self, snapshot: dict) -> None:
        """Lean backfill: replay only the replicated stores (desired
        rows + rank table) out of an api/snapshot capture. The full
        restore path (route cache, audit baselines, traffic EWMA)
        stays per-replica — those planes rebuild from the fabric."""
        for rank_str, mac in (snapshot.get("rankdb") or {}).items():
            self._apply_rank(("add", int(rank_str), mac))
        rows = (snapshot.get("desired_flows") or {}).get("rows", ())
        for row in rows:
            dpid, src, dst, out_port, rewrite, collective = row
            self._apply_desired((
                "record", int(dpid), src, dst, int(out_port), rewrite,
                bool(collective),
            ))

    # -- lease + adoption --

    def _check_leases(self, now: float) -> None:
        timeout = self.config.replica_lease_timeout_s
        for peer in self._peers():
            if not self._peer_alive.get(peer, True):
                continue
            last = self._last_heard.get(peer)
            if last is None:
                self._last_heard[peer] = now  # lease grace starts now
            elif now - last > timeout:
                self._expire(peer, now)

    def _expire(self, peer: int, now: float) -> None:
        self._peer_alive[peer] = False
        _m_lease_expiries.inc()
        log.warning("replica %d: peer %d lease expired; adopting its "
                    "shards", self.index, peer)
        self.bus.publish(ev.EventPeerLeaseExpired(peer))
        for shard in self.ownership.shards_of(peer):
            epoch = self.ownership.adopt(shard)
            _m_adoptions.inc()
            self.bus.publish(ev.EventShardAdopted(shard, epoch, self.index))
        _m_epoch.set(max(self.ownership.epoch.values(), default=0))
        # replay any tail the dead peer shipped before it stopped
        for msg in self.link.recv():
            self._handle(msg, now)
        # reconstruct the adopted shard's switch liveness: the peer's
        # last heartbeat, corrected by lifecycle events that went
        # unowned after the death
        dpids = set(self._peer_dps.get(peer, ()))
        if self.mux is not None:
            live, down = self.mux.take_orphans()
            dpids |= set(live)
            dpids -= set(down)
        dpids = {
            int(d) for d in dpids
            if self.ownership.owns(d) and d not in self.router.dps
        }
        # jittered republish: each EventDatapathUp rides the Router's
        # budgeted reconcile path and the audit verify queue — the
        # rate-shaped, audit-verified re-drive, de-synchronized so a
        # pair-wide failover can't thundering-herd the fabric
        base = self.config.replica_adopt_backoff_s
        jitter = self.router.recovery.jitter
        for d in sorted(dpids):
            self._adopt_due.append((now + jitter(base), d))

    def _drain_adoptions(self, now: float) -> None:
        if not self._adopt_due:
            return
        ready = [x for x in self._adopt_due if x[0] <= now]
        if not ready:
            return
        self._adopt_due = [x for x in self._adopt_due if x[0] > now]
        audit = self.controller.audit
        for _t, dpid in sorted(ready):
            if dpid in self.router.dps:
                continue
            self.bus.publish(ev.EventDatapathUp(dpid))
            if audit is not None:
                audit.request_verify(dpid)

    def _drain_redrives(self) -> None:
        budget = self.config.replica_redrive_per_tick or len(self._redrive_q)
        desired = self.router.recovery.desired
        while self._redrive_q and budget > 0:
            budget -= 1
            dpid = self._redrive_q.popleft()
            keys = self._redrive_rows.pop(dpid, set())
            dels = self._delete_rows.pop(dpid, set())
            if dpid not in self.router.dps:
                continue  # reconcile-on-connect covers it instead
            if dels:
                self.router.audit_delete(dpid, sorted(dels))
            rows = [
                (s, d, spec) for s, d, spec in desired.entries_for(dpid)
                if (s, d) in keys
            ]
            if rows:
                self.router.audit_redrive(dpid, rows)

    # -- observability --

    def status(self) -> dict:
        """Forensics payload: the flight recorder's "replica" context
        and the ``replica_status`` pull RPC."""
        return {
            "mode": "pair",
            "index": self.index,
            "ownership": self.ownership.to_dict(),
            "seq_out": self._seq_out,
            "seq_in": self._seq_in,
            "staged": len(self._staged),
            "peer_acked": self._peer_acked,
            "lag": max(0, self._seq_out - self._peer_acked),
            "peer_alive": {
                p: self._peer_alive.get(p, True) for p in self._peers()
            },
            "adopt_queue": len(self._adopt_due),
            "redrive_queue": len(self._redrive_q),
            "need_backfill": self._need_backfill,
        }


# -- pair harness ----------------------------------------------------------


@dataclasses.dataclass
class ControllerPair:
    """Two controllers over one shared fabric — the chaos-acceptance
    and benchmark harness (and the reference wiring for launch mode)."""

    fabric: object
    mux: PairBus
    controllers: list
    proxies: list
    links: tuple

    def plane(self, index: int):
        return self.controllers[index].replica

    def attach(self) -> None:
        self.fabric.connect(self.mux)
        self.fabric.on_idle = self._idle

    def _idle(self) -> None:
        for i, proxy in enumerate(self.proxies):
            cb = proxy.on_idle
            if cb is not None and i not in self.mux.dead:
                cb()

    def kill(self, index: int) -> None:
        """Model controller ``index`` dying: no more events, no more
        replication traffic, its heartbeats stop."""
        self.mux.kill(index)
        self.links[index].kill()

    def poll(self, now: float) -> None:
        """One Monitor pass per live controller — the EventStatsFlush
        edge that drives anti-entropy, audit, and the replica tick."""
        for i, c in enumerate(self.controllers):
            if i not in self.mux.dead:
                c.monitor.poll(now=now)

    def survivor(self):
        alive = [c for i, c in enumerate(self.controllers)
                 if i not in self.mux.dead]
        return alive[0] if alive else None


def build_pair(fabric, config, clock: Callable[[], float] = time.monotonic,
               count: int = 2) -> ControllerPair:
    """Wire ``count`` controllers (practically 2) over one shared
    fabric: per-replica OwnershipMap + FencedSouthbound, a LoopLink
    mesh pair, and the PairBus mux. Call ``pair.attach()`` to connect
    the fabric (NOT controller.attach())."""
    from sdnmpi_tpu.control.controller import Controller

    if count != 2:
        raise NotImplementedError("LoopLink harness is a pair (count=2)")
    links = LoopLink.pair()
    mux = PairBus()
    controllers, proxies = [], []
    for i in range(count):
        om = OwnershipMap(count, i)
        proxy = FencedSouthbound(fabric, om, shared=True)
        c = Controller(proxy, config, ownership=om, replica_link=links[i])
        c.replica.clock = clock
        c.replica.mux = mux
        mux.register(i, c.bus, om)
        controllers.append(c)
        proxies.append(proxy)
    pair = ControllerPair(fabric, mux, controllers, proxies, links)
    return pair
