"""Fabric ground-truth audit plane (ISSUE 15).

Every earlier observability layer (PRs 4/7/14) instruments the
controller's OWN pipeline; nothing observed the fabric. Installed state
was asserted only in tests, the recovery plane's wipe-and-resync
escalation trusted the wipe, and a switch silently corrupting its table
— a row dropped by a firmware bug, a row inserted by a rogue writer, a
counter ASIC going dead — was invisible forever. This module is the
independent ground-truth channel:

- **Sweep**: per ``EventStatsFlush`` a shard of the switch space
  answers OFPST_FLOW (``southbound.flow_stats``; the wire codec is
  protocol/ofwire.py, multipart), paced by
  ``Config.audit_switches_per_flush`` so a 1024-switch fabric audits in
  bounded round-robin slices — the install plane's ``install_highwater``
  idiom applied to the stats plane.
- **Diff**: replies canonicalize to the Router's install scope (the
  default-priority exact-L2 rows with cookie 0 — bootstrap control
  rules and block-plane rows are other subsystems' property) and diff
  against the :class:`~sdnmpi_tpu.control.recovery.DesiredFlowStore`
  three ways: **missing** desired rows (absent, or present with the
  wrong actions — a blackholed row is a missing desired row), **orphan**
  rows the store never recorded, and **counter-dead** rows that should
  carry traffic (their pair's counters advance on other switches while
  this row stays flat across consecutive sweeps — the dead-counter /
  diverted-traffic signature).
- **Confirm, then heal**: a suspected divergence must survive
  ``Config.audit_confirm_sweeps`` consecutive sweeps before it is
  confirmed — one-sweep transients (a packet-out-bypassed first packet,
  an install racing the sweep) clear themselves — and switches whose
  recovery machinery is mid-air (``RecoveryPlane.in_flight``) are
  skipped entirely: their gap is already being repaired. Confirmed rows
  count into ``fabric_divergence_total{kind}``, feed the PR-5
  reconcile/resync path as TARGETED re-drives (missing/dead rows
  reinstall through ``Router.audit_redrive`` — OF 1.0 ADD replaces the
  corrupt entry; orphans tear down through ``Router.audit_delete``),
  and freeze a flight-recorder bundle naming the switch and the rows
  (:class:`FabricDivergence`). The wipe-and-resync escalation now ends
  with a verify sweep (``request_verify``) instead of blind trust.
- **Attribution**: the same sweep's per-row byte deltas roll up by
  tenant (admission MAC groups; unregistered sources pool under "-")
  into ``fabric_tenant_bytes_total{tenant}`` and by collective (the
  phase-row index of :class:`~sdnmpi_tpu.core.collective_table.
  CollectiveInstall`) into the congestion report's measured-vs-modeled
  column — the first time the PR-8 scheduler's modeled completion can
  be checked against observed bytes.

FatPaths-style multipath steering (arxiv 1906.10885) and the SLO plane
both ultimately steer on per-flow traffic truth; this plane is where
that truth enters the controller.
"""

from __future__ import annotations

import collections
import time
from typing import Optional

from sdnmpi_tpu.control.ownership import is_owner_cookie
from sdnmpi_tpu.protocol import openflow as of
from sdnmpi_tpu.utils.metrics import LATENCY_BUCKETS_S, REGISTRY
from sdnmpi_tpu.utils.tracing import start_span

_m_sweeps = REGISTRY.counter(
    "audit_sweeps_total", "fabric audit sweep passes (per EventStatsFlush)"
)
_m_sweep_s = REGISTRY.histogram(
    "audit_sweep_seconds", LATENCY_BUCKETS_S,
    "wall of one audit sweep pass (flow-stats pull + canonicalize + "
    "diff + heal over the pass's switch shard)",
)
_m_rows = REGISTRY.counter(
    "audit_rows_checked_total",
    "installed flow rows canonicalized and diffed against the desired "
    "store",
)
_m_skipped = REGISTRY.counter(
    "audit_switches_skipped_total",
    "audit passes skipped for one switch (recovery in flight, or no "
    "stats reply this pull)",
)
_m_divergence = REGISTRY.labeled_counter(
    "fabric_divergence_total", "kind",
    "confirmed installed-vs-desired divergences, by kind "
    "(missing / orphan / counter_dead)",
)
_m_diverged = REGISTRY.gauge(
    "fabric_diverged_switches",
    "switches with a confirmed divergence in the latest audit pass "
    "that covered them",
)
_m_healed = REGISTRY.counter(
    "audit_heals_total",
    "targeted repair rows driven by the audit plane (re-installed "
    "missing/dead rows + deleted orphans)",
)
_m_tenant_bytes = REGISTRY.labeled_counter(
    "fabric_tenant_bytes_total", "tenant",
    "measured data-plane bytes attributed per tenant from flow-stats "
    "deltas (admission MAC groups; unregistered sources pool under -)",
)


#: sweep intervals the congestion report's measured block averages
#: over — long enough to smooth pull jitter, short enough that the
#: measured-vs-modeled comparison tracks the current workload
REPORT_WINDOW_SWEEPS = 8


def _parse_row_actions(actions) -> Optional[tuple[int, Optional[str]]]:
    """(out_port, rewrite MAC | None) of a Router-shaped action tuple,
    None when the layout is not one the Router installs (including the
    empty/drop layout a blackhole mutation leaves behind)."""
    if len(actions) == 1 and isinstance(actions[0], of.ActionOutput):
        return actions[0].port, None
    if (
        len(actions) == 2
        and isinstance(actions[0], of.ActionSetDlDst)
        and isinstance(actions[1], of.ActionOutput)
    ):
        return actions[1].port, actions[0].mac
    return None


class FabricDivergence:
    """Flight-recorder trigger: any advance of the
    ``fabric_divergence_total`` family freezes a bundle whose detail
    names the diverged switches and rows (every confirmed divergence is
    an incident — the fabric disagreed with the controller)."""

    name = "fabric:divergence"

    def __init__(self, plane: "AuditPlane") -> None:
        self.plane = plane

    @staticmethod
    def _total(snapshot: dict) -> int:
        pfx = "fabric_divergence_total{"
        return sum(
            v for k, v in snapshot.get("counters", {}).items()
            if k.startswith(pfx)
        )

    def check(self, prev: dict, cur: dict, window=None) -> Optional[dict]:
        d = self._total(cur) - self._total(prev)
        if d <= 0:
            return None
        return {
            "divergences": int(d),
            "recent": self.plane.take_unreported(),
        }


class AuditPlane:
    """Continuous fabric audit (module docstring). Single-threaded by
    bus discipline like every control-plane store; ``sweep`` is the one
    entry point, driven per ``EventStatsFlush`` by the Controller."""

    def __init__(self, config, southbound, router,
                 clock=time.monotonic) -> None:
        self.config = config
        self.southbound = southbound
        self.router = router
        self.recovery = router.recovery
        self.clock = clock
        #: round-robin pacing cursor over the sorted live switch list
        self._cursor = 0
        #: completed full passes over the switch space — the audit's
        #: sweep-period clock (counter-dead pair epochs key on it)
        self.cycle = 0
        #: switches owed a priority verify sweep (wipe-and-resync ends
        #: with verification instead of blind trust)
        self._verify: set[int] = set()
        #: dpid -> {(src, dst): (packet_count, byte_count)} as of the
        #: last sweep that covered the switch — the delta baseline for
        #: attribution and counter-dead detection
        self._counters: dict[int, dict] = {}
        #: (src, dst) -> cycle at which the pair's counters last
        #: advanced on ANY switch (the path-consistency signal)
        self._pair_epoch: dict[tuple[str, str], int] = {}
        #: (src, dst) -> cycle at which a TABLE-VISIBLE gap (a missing
        #: or mismatched row) was last seen for the pair on any switch.
        #: Counter-dead is suppressed for such pairs: a blackholed hop
        #: starves every hop downstream of it, and flagging the starved
        #: rows too would double-count one corruption — counter-dead
        #: exists for faults the table dump CANNOT show (dead counter
        #: ASIC, diverted traffic), so it only fires when the table
        #: looks right
        self._pair_gap: dict[tuple[str, str], int] = {}
        #: dpid -> {(kind, (src, dst)): consecutive sightings} awaiting
        #: confirmation; cleared when a sweep stops seeing them
        self._suspects: dict[int, dict] = {}
        #: dpids whose latest covering pass confirmed divergence
        self._diverged: set[int] = set()
        #: confirmed-divergence records, newest last (bundle forensics)
        self.recent: collections.deque = collections.deque(maxlen=64)
        #: records not yet shipped in a trigger detail
        self._unreported: list[dict] = []
        self._seq = 0
        #: cookie -> measured bytes of its phase rows (the congestion
        #: report's measured-vs-modeled column)
        self.collective_bytes: dict[int, int] = {}
        self._indexed_cookies: frozenset = frozenset()
        self._cookie_idx: dict = {}
        #: measured traffic matrix fed per attributed source-edge byte
        #: delta (oracle/trafficplane.py; wired by the Controller)
        self.traffic = None
        #: (clock, tenant-bytes, collective-bytes) register snapshots
        #: taken at each sweep close — the windowed measured block that
        #: report() diffs (lifetime counters vs an instantaneous model
        #: would be dimensionally dishonest)
        self._window: collections.deque = collections.deque(
            maxlen=REPORT_WINDOW_SWEEPS + 1
        )

    # -- wiring seams ------------------------------------------------------

    def trigger(self) -> FabricDivergence:
        return FabricDivergence(self)

    def take_unreported(self) -> list[dict]:
        out, self._unreported = self._unreported, []
        return out

    def request_verify(self, dpid: int) -> None:
        """Queue a priority audit of one switch ahead of the round-robin
        cursor — the verify leg a wipe-and-resync escalation ends with.
        Southbounds that cache table dumps (the one-interval-lag TCP
        pull) drop theirs: the verify must diff a post-wipe dump, not
        the table as it stood before the escalation."""
        self._verify.add(dpid)
        invalidate = getattr(
            self.southbound, "invalidate_flow_stats", None
        )
        if invalidate is not None:
            invalidate(dpid)

    def forensics(self) -> dict:
        """Flight-bundle context: where the sweep is and what it has
        confirmed — the 'is the fabric lying to me' half of an incident."""
        return {
            "cycle": self.cycle,
            "cursor": self._cursor,
            "diverged_switches": sorted(self._diverged),
            "suspects": {
                dpid: sorted(
                    f"{kind}:{src}>{dst}"
                    for (kind, (src, dst)) in table
                )
                for dpid, table in self._suspects.items() if table
            },
            "recent": list(self.recent)[-8:],
            "collective_bytes": dict(self.collective_bytes),
        }

    def report(self) -> dict:
        """The congestion report's measured block, WINDOWED: byte
        deltas and rates over the last :data:`REPORT_WINDOW_SWEEPS`
        sweep intervals per tenant and per collective install, beside
        each install's MODELED congestion figure. The old block put
        lifetime-cumulative counters next to an instantaneous modeled
        figure — a long-lived tenant dwarfed any model simply by being
        old — so the measured column is now a delta/rate over the
        audit's own sweep clock (lifetime totals stay available under
        ``*_total`` keys)."""
        live = {i.cookie: i for i in self.router.collectives}
        for cookie in list(self.collective_bytes):
            if cookie not in live:
                del self.collective_bytes[cookie]
        if len(self._window) >= 2:
            t0, tenants0, colls0 = self._window[0]
            t1, tenants1, colls1 = self._window[-1]
            window_s = max(t1 - t0, 0.0)
        else:
            # fewer than two sweep edges: the window IS the lifetime
            tenants0, colls0 = {}, {}
            tenants1 = dict(_m_tenant_bytes.values)
            colls1 = dict(self.collective_bytes)
            window_s = 0.0
        rate = (1.0 / window_s) if window_s > 0.0 else 0.0
        tenant_win = {
            t: int(tenants1.get(t, 0) - tenants0.get(t, 0))
            for t in sorted(set(tenants0) | set(tenants1))
        }
        return {
            "window_s": window_s,
            "window_sweeps": max(len(self._window) - 1, 0),
            "tenant_bytes": tenant_win,
            "tenant_bps": {t: v * rate for t, v in tenant_win.items()},
            "tenant_bytes_total": {
                t: int(v) for t, v in sorted(_m_tenant_bytes.values.items())
            },
            "collectives": [
                {
                    "cookie": cookie,
                    "measured_bytes": int(
                        colls1.get(cookie, 0) - colls0.get(cookie, 0)
                    ),
                    "measured_bps": (
                        colls1.get(cookie, 0) - colls0.get(cookie, 0)
                    ) * rate,
                    "measured_bytes_total": int(
                        self.collective_bytes.get(cookie, 0)
                    ),
                    "modeled_congestion": float(inst.max_congestion),
                    "n_phases": inst.n_phases,
                }
                for cookie, inst in sorted(live.items())
            ],
        }

    # -- the sweep ---------------------------------------------------------

    def sweep(self, now: Optional[float] = None) -> list[dict]:
        """One audit pass: queued verify requests first, then this
        flush's round-robin shard — BOTH under the per-flush pacing cap
        (a mass resync's verify queue must not turn one flush into the
        full-fabric burst the pacing exists to prevent; the overflow
        stays queued). Returns the pass's confirmed-divergence records
        (empty almost always)."""
        live = set(self.router.dps)
        # departed switches carry no audit state: their baselines are
        # moot (a redial resets counters anyway), their suspects can
        # never re-confirm, and a crashed switch must not pin the
        # diverged gauge nonzero forever
        self._diverged &= live
        self._verify &= live
        for table in (self._counters, self._suspects):
            for d in [d for d in table if d not in live]:
                del table[d]
        dpids = sorted(live)
        if not dpids:
            return []
        per = int(self.config.audit_switches_per_flush)
        take = len(dpids) if per <= 0 else min(per, len(dpids))
        verify = sorted(self._verify)[:take]
        self._verify.difference_update(verify)
        room = take - len(verify)
        start = self._cursor % len(dpids)
        shard = [dpids[(start + i) % len(dpids)] for i in range(room)]
        if room and start + room >= len(dpids):
            self.cycle += 1  # a full pass over the switch space closed
            # pair epochs/gaps older than the detector's horizon are
            # dead weight (it only ever reads >= cycle - 1): prune so
            # endpoint churn cannot grow the pair dicts forever
            stale = self.cycle - 2
            for table in (self._pair_epoch, self._pair_gap):
                for k in [k for k, v in table.items() if v < stale]:
                    del table[k]
        self._cursor = (start + room) % len(dpids)
        verify_set = set(verify)
        chosen = verify + [d for d in shard if d not in verify_set]

        t0 = time.perf_counter()
        sp = start_span("audit_sweep", n_switches=len(chosen))
        confirmed: list[dict] = []
        try:
            for dpid in chosen:
                result = self._audit_switch(dpid)
                if result is None:
                    # skipped (recovery mid-air / no stats reply): a
                    # VERIFY request is owed an actual audit — re-queue
                    # it instead of silently trusting the wipe after all
                    if dpid in verify_set:
                        self._verify.add(dpid)
                    continue
                confirmed.extend(result)
        finally:
            sp.end(n_confirmed=len(confirmed))
            _m_sweeps.inc()
            _m_sweep_s.observe(time.perf_counter() - t0)
            _m_diverged.set(len(self._diverged))
        # close the sweep on the report window: the measured block
        # diffs these register snapshots instead of lifetime totals
        self._window.append((
            self.clock(),
            dict(_m_tenant_bytes.values),
            dict(self.collective_bytes),
        ))
        return confirmed

    def _audit_switch(self, dpid: int) -> Optional[list[dict]]:
        """Audit ONE switch: pull, canonicalize, diff, attribute,
        confirm, heal. Returns confirmed-divergence records — or None
        when the switch could not be audited this pass (the caller
        re-queues verify requests on None)."""
        # recovery owns this gap; auditing it is noise — a reconcile
        # parked in the rate-shaping FIFO (e.g. an ISSUE-20 adoption
        # re-drive mid-air) counts as in flight
        if self.recovery.in_flight(dpid) or dpid in self.router._reconcile_pending:
            _m_skipped.inc()
            return None
        entries = self.southbound.flow_stats(dpid)
        if entries is None:
            _m_skipped.inc()
            return None  # no reply this pull — NOT an empty table
        prio = self.config.priority_default
        installed: dict[tuple[str, str], tuple] = {}
        for e in entries:
            m = e.match
            if (
                e.priority != prio
                or (e.cookie and not is_owner_cookie(e.cookie))
                or m.dl_src is None or m.dl_dst is None
            ):
                # bootstrap/control rules and block-plane rows;
                # ownership-epoch cookies on unicast rows (ISSUE 20)
                # stay in scope — cookie is 0 with the pair off
                continue
            installed[(m.dl_src, m.dl_dst)] = (
                _parse_row_actions(e.actions), e.packet_count, e.byte_count
            )
        _m_rows.inc(len(installed))
        desired = {
            (s, d): spec
            for s, d, spec in self.recovery.desired.entries_for(dpid)
        }

        advanced, flat = self._attribute(dpid, installed)

        missing = [
            row for row, spec in desired.items()
            if row not in installed
            or installed[row][0] != (spec.out_port, spec.rewrite)
        ]
        orphans = [row for row in installed if row not in desired]
        for row in missing:
            self._pair_gap[row] = self.cycle
        # counter-dead: the row exists and matches its spec, but its
        # counters stayed flat across a sweep interval in which the
        # SAME pair's counters advanced on other switches — traffic is
        # flowing and this hop is not seeing (or not counting) it.
        # Pairs with a recent table-visible gap are suppressed (see
        # _pair_gap): the gap already explains the dead counters.
        horizon = self.cycle - 1
        dead = [
            row for row in flat
            if row in desired and row not in missing
            and self._pair_epoch.get(row, -1) >= horizon
            and self._pair_gap.get(row, -(1 << 30)) < horizon
        ]
        return self._confirm(dpid, missing, orphans, dead, desired)

    def _attribute(self, dpid: int, installed: dict):
        """Per-row counter deltas vs the last covering sweep: roll
        bytes up by tenant and by collective, remember the fresh
        baseline, and report which rows advanced vs stayed flat (the
        counter-dead inputs). Counter RESETS (an OF 1.0 ADD replacing
        the entry) re-baseline without attributing stale history."""
        prev = self._counters.get(dpid, {})
        tenants = self.router.admission
        registered = tenants._tenants
        cookie_idx = self._cookie_index()
        advanced: list = []
        flat: list = []
        fresh: dict = {}
        for row, (_act, pkts, bts) in installed.items():
            fresh[row] = (pkts, bts)
            last = prev.get(row)
            if last is None:
                continue  # first sight: baseline only
            if pkts < last[0] or bts < last[1]:
                continue  # counters reset (entry replaced): re-baseline
            d_bytes = bts - last[1]
            if pkts > last[0] or d_bytes > 0:
                advanced.append(row)
                self._pair_epoch[row] = self.cycle
            else:
                flat.append(row)
            if d_bytes > 0:
                src = row[0]
                tenant = registered.get(src)
                _m_tenant_bytes.inc(
                    tenant if tenant is not None else "-", d_bytes
                )
                if self.traffic is not None:
                    # the plane itself enforces source-edge attribution
                    # (each flow's bytes enter the matrix once, not
                    # once per audited hop)
                    self.traffic.ingest(
                        dpid, src, row[1],
                        tenant if tenant is not None else "-", d_bytes,
                    )
                cookie = cookie_idx.get((dpid, row[0], row[1]))
                if cookie is not None:
                    self.collective_bytes[cookie] = (
                        self.collective_bytes.get(cookie, 0) + d_bytes
                    )
        self._counters[dpid] = fresh
        return advanced, flat

    def _cookie_index(self) -> dict:
        """(dpid, src, dst) -> cookie over the phase rows of every live
        scheduled install — rebuilt only when the cookie set changes
        (the rows are immutable per install)."""
        installs = [
            i for i in self.router.collectives if i.phase_rows is not None
        ]
        cookies = frozenset(i.cookie for i in installs)
        if cookies != self._indexed_cookies:
            from sdnmpi_tpu.utils.mac import int_to_mac_memo as _mac

            idx: dict = {}
            for inst in installs:
                for _phase, arr in inst.phase_rows:
                    for d, s, t in arr.tolist():
                        idx[(d, _mac(s), _mac(t))] = inst.cookie
            self._indexed_cookies = cookies
            self._cookie_idx = idx
        return self._cookie_idx

    def _confirm(self, dpid: int, missing, orphans, dead,
                 desired) -> list[dict]:
        """Promote repeat sightings to confirmed divergence and heal it
        (see module docstring). A suspicion not re-seen this pass is
        dropped — transients clear themselves."""
        need = max(1, int(self.config.audit_confirm_sweeps))
        prev = self._suspects.get(dpid, {})
        suspects: dict = {}
        confirmed: dict[str, list] = {}
        for kind, rows in (
            ("missing", missing), ("orphan", orphans),
            ("counter_dead", dead),
        ):
            # counter-dead FLOORS at two sightings regardless of the
            # config: one flat-while-pair-advanced interval is exactly
            # what ordinary traffic cessation looks like (the pair's
            # last packets landed before this hop's baseline) — only
            # table-visible kinds may confirm on first sight
            k_need = max(need, 2) if kind == "counter_dead" else need
            for row in rows:
                key = (kind, row)
                count = prev.get(key, 0) + 1
                if count >= k_need:
                    confirmed.setdefault(kind, []).append(row)
                else:
                    suspects[key] = count
        if suspects:
            self._suspects[dpid] = suspects
        else:
            self._suspects.pop(dpid, None)
        if not confirmed:
            self._diverged.discard(dpid)
            return []

        self._diverged.add(dpid)
        records: list[dict] = []
        for kind, rows in confirmed.items():
            _m_divergence.inc(kind, len(rows))
            self._seq += 1
            rec = {
                "seq": self._seq,
                "dpid": dpid,
                "kind": kind,
                "rows": sorted(f"{s}>{d}" for s, d in rows),
            }
            self.recent.append(rec)
            self._unreported.append(rec)
            records.append(rec)
        # heal: targeted re-drives through the PR-5 reconcile path —
        # one row each, never a wipe. The re-driven entry's counters
        # reset, so its baseline is dropped (next sweep re-baselines).
        redrive = sorted(
            set(confirmed.get("missing", ()))
            | set(confirmed.get("counter_dead", ()))
        )
        if redrive:
            self.router.audit_redrive(
                dpid, [(s, d, desired[(s, d)]) for s, d in redrive]
            )
            _m_healed.inc(len(redrive))
            baselines = self._counters.get(dpid, {})
            for row in redrive:
                baselines.pop(row, None)
        delete = sorted(confirmed.get("orphan", ()))
        if delete:
            self.router.audit_delete(dpid, delete)
            _m_healed.inc(len(delete))
            baselines = self._counters.get(dpid, {})
            for row in delete:
                baselines.pop(row, None)
        return records
