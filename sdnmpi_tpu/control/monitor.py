"""Port-statistics monitor app.

Equivalent of the reference's ``Monitor`` (reference: sdnmpi/monitor.py:21-94):
polls per-port counters of every live datapath on an interval, converts
cumulative counters into rx/tx packets-per-second and bytes-per-second
deltas, and logs one TSV line per port
(``dpid  port  rx_pps  rx_bps  tx_pps  tx_bps``, monitor.py:87-88).

Beyond the reference, every sample is also published as ``EventPortStats``
so the TopologyManager can maintain the per-link utilization tensor that
feeds congestion-aware routing — turning the monitor stream from a log
file into an input of the path oracle (SURVEY §5 north star).

``poll(now)`` performs one synchronous sampling pass (tests inject
timestamps); ``run()`` is the asyncio polling loop used by the CLI, taking
the place of the reference's green thread (monitor.py:32,47-52).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Optional

from sdnmpi_tpu.config import Config, DEFAULT_CONFIG
from sdnmpi_tpu.control import events as ev
from sdnmpi_tpu.control.bus import EventBus
from sdnmpi_tpu.utils.metrics import REGISTRY

log = logging.getLogger("Monitor")

_m_passes = REGISTRY.counter(
    "monitor_passes_total",
    "completed port-stats sampling passes (the telemetry feed cadence)",
)
_m_samples = REGISTRY.counter(
    "monitor_port_samples_total", "per-port throughput samples published"
)
# shared with control/southbound.py (which discards the stale cached
# StatsReply on a FEATURES_REPLY redial): both sites count the same
# phenomenon — per-connection stats state outliving its connection
_m_stale_stats = REGISTRY.counter(
    "monitor_stale_stats_total",
    "stale cached port-stats state discarded when a datapath redialed",
)


@dataclasses.dataclass
class _PortSample:
    timestamp: float
    rx_packets: int
    rx_bytes: int
    tx_packets: int
    tx_bytes: int


class Monitor:
    name = "Monitor"

    def __init__(
        self,
        bus: EventBus,
        southbound,
        config: Config = DEFAULT_CONFIG,
    ) -> None:
        self.bus = bus
        self.southbound = southbound
        self.config = config
        self.datapaths: set[int] = set()
        #: dpid -> port_no -> last sample (reference: monitor.py:29-31)
        self.datapath_stats: dict[int, dict[int, _PortSample]] = {}

        bus.subscribe(ev.EventDatapathUp, self._datapath_up)
        bus.subscribe(ev.EventDatapathDown, self._datapath_down)

    def _datapath_up(self, event: ev.EventDatapathUp) -> None:
        self.datapaths.add(event.dpid)
        if self.datapath_stats.get(event.dpid):
            # an Up without a Down in between is a redial race (or a
            # recovery-plane resync): the switch's counters restarted
            # from zero, so the old baselines would differentiate into
            # negative garbage — re-baseline from scratch
            _m_stale_stats.inc()
            self.datapath_stats[event.dpid] = {}
        else:
            self.datapath_stats.setdefault(event.dpid, {})

    def _datapath_down(self, event: ev.EventDatapathDown) -> None:
        self.datapaths.discard(event.dpid)
        self.datapath_stats.pop(event.dpid, None)

    # -- sampling ---------------------------------------------------------

    def poll(self, now: Optional[float] = None) -> None:
        """One sampling pass over every live datapath. The end of the
        pass publishes EventStatsFlush so utilization consumers ingest
        the pass's samples as one vectorized batch (the device
        utilization plane's scatter cadence)."""
        for dpid in sorted(self.datapaths):
            self._poll_one(dpid, time.time() if now is None else now)
        _m_passes.inc()
        self.bus.publish(ev.EventStatsFlush())

    def _poll_one(self, dpid: int, now: float) -> None:
        """Sample one datapath — the unit shared by the synchronous
        poll() and the sliced async loop."""
        stats = self.southbound.port_stats(dpid)
        self._ingest(dpid, stats, now)

    def _ingest(self, dpid: int, stats, now: float) -> None:
        per_port = self.datapath_stats.setdefault(dpid, {})
        for stat in sorted(stats, key=lambda s: s.port_no):
            last = per_port.get(stat.port_no)
            if last is None:
                # first sample establishes the baseline
                # (reference: monitor.py:70-77)
                per_port[stat.port_no] = _PortSample(
                    now, stat.rx_packets, stat.rx_bytes, stat.tx_packets, stat.tx_bytes
                )
                continue

            dt = now - last.timestamp
            if dt <= 0:
                continue
            rx_pps = (stat.rx_packets - last.rx_packets) / dt
            rx_bps = (stat.rx_bytes - last.rx_bytes) / dt
            tx_pps = (stat.tx_packets - last.tx_packets) / dt
            tx_bps = (stat.tx_bytes - last.tx_bytes) / dt

            # TSV stream, same columns as the reference (monitor.py:87-88)
            log.info(
                "%016x\t%d\t%d\t%d\t%d\t%d",
                dpid,
                stat.port_no,
                rx_pps,
                rx_bps,
                tx_pps,
                tx_bps,
            )
            _m_samples.inc()
            self.bus.publish(
                ev.EventPortStats(dpid, stat.port_no, rx_pps, rx_bps, tx_pps, tx_bps)
            )

            per_port[stat.port_no] = _PortSample(
                now, stat.rx_packets, stat.rx_bytes, stat.tx_packets, stat.tx_bytes
            )

    #: datapaths polled per event-loop slice in the async loop
    POLL_SLICE = 64

    async def run(self) -> None:
        """Asyncio polling loop (CLI profile with monitoring enabled).

        The pass over datapaths is sliced: control returns to the event
        loop every POLL_SLICE switches, so a 1,000-switch fabric cannot
        starve the RPC mirror or packet handling for a whole sampling
        pass. Slicing (not a worker thread) keeps the single-threaded
        bus discipline — handlers never run concurrently (SURVEY §5
        race-discipline equivalent)."""
        import asyncio

        log.debug("Starting monitor loop")
        loop = asyncio.get_running_loop()
        while True:
            started = loop.time()
            for i, dpid in enumerate(sorted(self.datapaths)):
                if dpid not in self.datapaths:
                    continue  # went down while we were yielding
                self._poll_one(dpid, time.time())
                if (i + 1) % self.POLL_SLICE == 0:
                    await asyncio.sleep(0)
            # one vectorized utilization flush per pass (see poll())
            _m_passes.inc()
            self.bus.publish(ev.EventStatsFlush())
            elapsed = loop.time() - started
            await asyncio.sleep(
                max(0.0, self.config.monitor_interval - elapsed)
            )
