"""Serving SLO plane (ISSUE 14): per-tenant objectives, multi-window
burn rates, and the flight-recorder trigger that freezes a diagnostic
bundle when a tenant's error budget burns.

The serving plane (PR 11) gave every tenant a latency distribution; an
operator needs the next layer up: *objectives* over those
distributions, evaluated the way the SRE literature evaluates them
(Beyer et al., *The Site Reliability Workbook*, ch. 5 — multi-window,
multi-burn-rate alerts):

- a :class:`SLOTarget` per tenant — ``p99_ms`` ("99% of routed requests
  complete under this many milliseconds") and ``availability`` ("this
  fraction of offered requests is served, not rejected/dropped");
- per-tenant latency histograms
  (``slo_route_latency_seconds{tenant=...}``) fed by the Router at
  window completion — park-to-install, the latency a tenant's MPI rank
  actually experiences — plus the admission plane's per-tenant
  rejection counters for the availability side;
- **burn rate** = (error fraction of the interval) / (error budget of
  the objective). Burning at 1.0 exactly spends the budget; a p99
  objective (budget 1%) with 10% of an interval's requests provably
  over target burns at 10x.
- the :class:`SLOBurn` trigger evaluates TWO windows per
  ``EventStatsFlush``, both scaled to the flush cadence instead of
  wall-clock minutes (the control plane's "hour" is however many
  flushes the Monitor performs in one): the **fast** window (the last
  flush interval) must burn AND the **slow** window (the last
  ``slow_flushes`` intervals) must burn. Fast-only would page on every
  blip; slow-only would page minutes after the incident started; the
  pair fires exactly while an incident is both fresh and sustained.

When the trigger fires, the frozen bundle names the burning tenant in
its ``detail`` and — through the ``slo`` context provider — the
**dominant pipeline stage** aggregated from the recorder's retained
span trees (self-time per span name), so the first page already says
"tenant=victim, stage=reap" instead of "something is slow".

Hot-path contract (the PR-4/7 rule): with no targets configured the
Router's per-request cost is one attribute load + is-None test
(``router.slo`` stays None); with targets, tenants NOT under an
objective cost one dict miss.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from sdnmpi_tpu.utils.metrics import LATENCY_BUCKETS_S, REGISTRY
from sdnmpi_tpu.utils.timeline import estimate_p99

#: the per-tenant request-latency family the Router feeds (window
#: park-to-install wall; see Router._finish_batch)
LATENCY_HIST = "slo_route_latency_seconds"

_m_latency = REGISTRY.labeled_histogram(
    LATENCY_HIST, "tenant", LATENCY_BUCKETS_S,
    "per-tenant route-request latency (coalescer park -> install), "
    "fed for tenants under an SLO target",
)
_m_burn = REGISTRY.labeled_counter(
    "slo_burn_triggers_total", "tenant",
    "SLO burn-rate trigger firings per tenant",
)


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """One tenant's serving objectives. ``p99_ms`` is the latency
    objective (99% under this bound — the error budget is the
    remaining 1%); ``availability`` is the served fraction of offered
    requests (budget = 1 - availability)."""

    tenant: str
    p99_ms: float
    availability: float = 0.999

    def __post_init__(self):
        if self.p99_ms <= 0:
            raise ValueError(f"slo target {self.tenant!r}: p99_ms must "
                             f"be > 0 (got {self.p99_ms})")
        if not 0.0 < self.availability < 1.0:
            raise ValueError(
                f"slo target {self.tenant!r}: availability must be in "
                f"(0, 1) (got {self.availability})"
            )


def parse_slo_target(spec: str) -> SLOTarget:
    """``tenant:p99_ms[:availability]`` -> :class:`SLOTarget` (the
    ``--slo-target`` CLI format; raises ValueError on malformed input
    so a typo fails the launch instead of silently not alerting)."""
    parts = spec.split(":")
    if len(parts) not in (2, 3) or not parts[0]:
        raise ValueError(
            f"--slo-target wants tenant:p99_ms[:availability], got "
            f"{spec!r}"
        )
    avail = float(parts[2]) if len(parts) == 3 else 0.999
    return SLOTarget(parts[0], float(parts[1]), avail)


def _hist_key(tenant: str) -> str:
    return f"{LATENCY_HIST}{{tenant={tenant}}}"


def _reject_key(tenant: str) -> str:
    return f"admission_rejections_total{{tenant={tenant}}}"


def _interval_burn(target: SLOTarget, base: dict, cur: dict,
                   min_count: int) -> Optional[dict]:
    """Burn rates of one interval (``base`` snapshot -> ``cur``), or
    None when the tenant served too few requests to judge (an idle
    tenant's lone outlier must not page anyone — the P99Regression
    rule). Latency badness uses the provably-above bucket semantics
    (HistogramThreshold): only observations in buckets whose LOWER
    edge is at/above the target count, so a histogram can never fire
    on values it cannot distinguish."""
    h1 = cur.get("histograms", {}).get(_hist_key(target.tenant))
    if h1 is None:
        return None
    h0 = (base or {}).get("histograms", {}).get(_hist_key(target.tenant))
    counts = list(h1["counts"])
    if h0 is not None and len(h0["counts"]) == len(counts):
        counts = [a - b for a, b in zip(counts, h0["counts"])]
    served = sum(counts)
    rej1 = cur.get("counters", {}).get(_reject_key(target.tenant), 0)
    rej0 = (base or {}).get("counters", {}).get(
        _reject_key(target.tenant), 0
    )
    rejected = max(0, rej1 - rej0)
    offered = served + rejected
    if offered < min_count:
        return None
    bounds = h1["buckets"]
    # NO clamp to the last finite edge (unlike HistogramThreshold,
    # where a dead trigger is the worse failure): clamping would count
    # +Inf-bucket observations BELOW an above-range target as provably
    # bad and page on a healthy tenant. Past the range the latency
    # side simply cannot prove a breach (SLOPlane warns at
    # construction); availability burn still fires.
    threshold = target.p99_ms / 1e3
    first = next(
        (i for i in range(1, len(counts))
         if float(bounds[i - 1]) >= threshold),
        len(counts),
    )
    slow = sum(counts[first:])
    latency_burn = (
        (slow / served) / 0.01 if served else 0.0
    )  # p99 objective: the error budget is the remaining 1%
    avail_budget = 1.0 - target.availability
    avail_burn = (rejected / offered) / avail_budget
    burn = max(latency_burn, avail_burn)
    return {
        "burn": burn,
        "slo": "latency" if latency_burn >= avail_burn else "availability",
        "latency_burn": round(latency_burn, 3),
        "availability_burn": round(avail_burn, 3),
        "served": int(served),
        "rejected": int(rejected),
        "slow_observations": int(slow),
        "p99_now_ms": round(estimate_p99(bounds, counts) * 1e3, 3),
    }


@dataclasses.dataclass
class SLOBurn:
    """Flight-recorder trigger: fire when ``target``'s error budget
    burns at >= ``burn_factor`` in BOTH the fast window (the last
    flush interval) and the slow window (the last ``slow_flushes``
    intervals of the recorder's rolling snapshot ring). Windows are
    flush-cadence-relative (see module docstring); a recorder younger
    than ``slow_flushes`` uses its whole history as the slow window,
    so a storm right after boot still fires."""

    target: SLOTarget
    burn_factor: float = 8.0
    slow_flushes: int = 12
    min_count: int = 16

    @property
    def name(self) -> str:
        return f"slo:{self.target.tenant}"

    def check(self, prev: dict, cur: dict, window=None) -> Optional[dict]:
        fast = _interval_burn(self.target, prev, cur, self.min_count)
        if fast is None or fast["burn"] < self.burn_factor:
            return None
        slow_base = prev
        if window:
            k = max(0, len(window) - self.slow_flushes)
            slow_base = window[k][1]
        slow = _interval_burn(self.target, slow_base, cur, self.min_count)
        if slow is None or slow["burn"] < self.burn_factor:
            return None
        _m_burn.inc(self.target.tenant)
        return {
            "tenant": self.target.tenant,
            "slo": fast["slo"],
            "p99_target_ms": self.target.p99_ms,
            "availability_target": self.target.availability,
            "burn_fast": round(fast["burn"], 3),
            "burn_slow": round(slow["burn"], 3),
            "burn_factor": self.burn_factor,
            "fast": fast,
            "slow": slow,
        }


def dominant_stage(trees) -> dict:
    """Aggregate SELF-time (wall minus child walls) per span name over
    completed span trees and name the dominant stage — the "where did
    the time go" half of an SLO page. Returns ``{"dominant_stage":
    name, "stage_self_ms": {name: total}}`` (empty when no trees)."""
    totals: dict[str, float] = {}
    for tree in trees:
        nodes = tree.get("nodes", {})
        for rec in nodes.values():
            wall = float(rec.get("wall_ms", 0.0))
            child_ms = sum(
                float(nodes[c].get("wall_ms", 0.0))
                for c in rec.get("children", ())
                if c in nodes
            )
            name = rec.get("name", "?")
            totals[name] = totals.get(name, 0.0) + max(
                0.0, wall - child_ms
            )
    if not totals:
        return {"dominant_stage": None, "stage_self_ms": {}}
    top = max(totals, key=lambda k: totals[k])
    return {
        "dominant_stage": top,
        "stage_self_ms": {
            k: round(v, 3)
            for k, v in sorted(totals.items(), key=lambda kv: -kv[1])
        },
    }


class SLOPlane:
    """Per-tenant SLO bookkeeping: owns the targets, the latency
    children the Router observes into, the trigger set, and the bundle
    forensics. Constructed by the Controller when
    ``Config.slo_targets`` is non-empty; ``router.slo`` points here."""

    def __init__(
        self,
        targets,
        admission,
        burn_factor: float = 8.0,
        slow_flushes: int = 12,
    ) -> None:
        self.targets: dict[str, SLOTarget] = {}
        if isinstance(targets, dict):
            # Config.slo_targets form: {tenant: (p99_ms, availability)}
            items = [
                spec if isinstance(spec, SLOTarget)
                else SLOTarget(name, *(
                    spec if isinstance(spec, (tuple, list)) else (spec,)
                ))
                for name, spec in targets.items()
            ]
        else:
            items = [
                parse_slo_target(t) if isinstance(t, str) else t
                for t in targets
            ]
        for t in items:
            self.targets[t.tenant] = t
        self.admission = admission
        self.burn_factor = float(burn_factor)
        self.slow_flushes = int(slow_flushes)
        #: tenant -> child histogram, pre-resolved so the per-request
        #: path is one dict get (targeted tenants only — cardinality is
        #: the operator's configured set, never request data)
        self._hists = {
            name: _m_latency.labels(name) for name in self.targets
        }
        #: tenants whose latency a load harness is currently feeding
        #: through :meth:`observe` — the Router's park-to-install feed
        #: SKIPS them so one served request is never counted twice
        #: (twice-counted good halves the burn fraction: an incident
        #: burning at 10x would read 5x and never page)
        self.harness_feed: set = set()
        for t in self.targets.values():
            if t.p99_ms / 1e3 > self._hists[t.tenant].bounds[-1]:
                # the histogram cannot DISTINGUISH values past its last
                # finite edge, so a target beyond it can never prove a
                # latency breach (availability burn still fires) — say
                # so once instead of silently never paging
                import logging

                logging.getLogger("slo").warning(
                    "slo target %s: p99 %.0f ms exceeds the latency "
                    "histogram's top bucket (%.0f ms); the latency burn "
                    "trigger cannot fire for it",
                    t.tenant, t.p99_ms,
                    self._hists[t.tenant].bounds[-1] * 1e3,
                )

    def observe_batch(self, batch, now: float) -> None:
        """Record every targeted tenant's park-to-install latency for
        one finished window (Router._finish_batch; ``now`` is
        time.monotonic, the clock ``t_parked`` was stamped on)."""
        tenant_of = self.admission.tenant_of
        hists = self._hists
        skip = self.harness_feed
        for p in batch:
            tenant = tenant_of(p.src)
            h = hists.get(tenant)
            if h is not None and p.t_parked and tenant not in skip:
                h.observe(now - p.t_parked)

    def observe(self, tenant: str, latency_s: float) -> None:
        """Record one request latency for a targeted tenant (no-op for
        untargeted names). The open-loop load harness feeds this with
        its schedule-anchored lateness (control/loadgen.py) — the
        latency a tenant EXPERIENCES includes the queueing before the
        controller ever parks the packet, which only the arrival
        schedule's owner can measure (the coordinated-omission point);
        the Router's park-to-install feed covers the in-controller
        half on production ingress."""
        h = self._hists.get(tenant)
        if h is not None:
            h.observe(latency_s)

    def triggers(self) -> list[SLOBurn]:
        return [
            SLOBurn(t, self.burn_factor, self.slow_flushes)
            for t in self.targets.values()
        ]

    def forensics(self, recorder=None) -> dict:
        """The ``slo`` context provider merged into every frozen
        bundle: the configured targets plus the dominant stage over
        the recorder's retained trees."""
        out: dict = {
            "targets": {
                n: {"p99_ms": t.p99_ms, "availability": t.availability}
                for n, t in self.targets.items()
            },
        }
        if recorder is not None:
            out.update(dominant_stage(recorder.trees()))
        return out
