"""Switch-ownership partition + epoch fencing for the controller pair.

ISSUE 20: an active/active controller pair must agree — without talking
— on WHICH controller programs WHICH switch, and a failed-over shard
must be able to prove, on the wire, which regime installed a row. Both
problems resolve into this module:

- :class:`OwnershipMap` — a deterministic partition of the switch space
  across ``count`` replicas. The shard function is pure arithmetic
  (``dpid % count``) so every replica computes the same answer with no
  coordination, mirroring how the mesh orders processes by
  ``(process_index, id)`` (shardplane/mesh.device_ring_order): replica
  order IS mesh order, so the partition is stable across restarts of
  the same job. Adoption (failover) flips a shard's *assignment* — the
  shard function never changes, only who serves it.
- **Epoch cookies** — every FlowMod a replica sends to an owned switch
  is stamped with a cookie encoding ``(shard, epoch)`` under a reserved
  tag byte. The epoch bumps on every adoption, so at quiesce the chaos
  acceptance can assert *no dual-owner installs*: a row stamped with a
  stale epoch was installed by the pre-failover regime and must have
  been re-stamped (OF 1.0 ADD replaces by match+priority) by the
  adopter's reconcile, or it is a fencing bug. The tag byte keeps the
  space disjoint from the block plane's small sequential collective
  cookies and the router's cookie-0 unicast rows.

Pure bookkeeping — no bus, no I/O; control/replica.py drives it.
"""

from __future__ import annotations

#: reserved tag byte (bits 56..63) marking a cookie as an ownership
#: token; collective cookies are small sequential ints and unicast rows
#: default to cookie 0, so the tag can never collide with either
OWNER_COOKIE_TAG = 0x5D

_TAG_SHIFT = 56
_SHARD_SHIFT = 24
_SHARD_MASK = 0xFFFF
_EPOCH_MASK = (1 << _SHARD_SHIFT) - 1


def cookie_token(shard: int, epoch: int) -> int:
    """The 64-bit cookie fencing one (shard, epoch) regime."""
    return (
        (OWNER_COOKIE_TAG << _TAG_SHIFT)
        | ((shard & _SHARD_MASK) << _SHARD_SHIFT)
        | (epoch & _EPOCH_MASK)
    )


def is_owner_cookie(cookie: int) -> bool:
    """True when ``cookie`` carries the ownership tag byte."""
    return (cookie >> _TAG_SHIFT) == OWNER_COOKIE_TAG


def decode_cookie(cookie: int) -> tuple[int, int]:
    """An owner cookie's ``(shard, epoch)``."""
    return (cookie >> _SHARD_SHIFT) & _SHARD_MASK, cookie & _EPOCH_MASK


def mesh_replica_index(count: int) -> int:
    """Derive this replica's index from the mesh's process order — the
    same ``(process_index, id)`` sort the shard plane rings devices by
    (shardplane/mesh.device_ring_order), truncated to process rank.
    Falls back to 0 when no distributed runtime is initialized, so a
    single-host launch without ``--ownership`` is replica 0 of 1."""
    try:
        import jax

        return int(jax.process_index()) % max(1, count)
    except Exception:
        return 0


class OwnershipMap:
    """Who serves each shard of the switch space, and at which epoch.

    ``shard_of`` is the fixed partition; ``assignment`` maps shard ->
    serving replica index and starts as the identity (shard i is served
    by replica i). :meth:`adopt` reassigns a dead peer's shard to this
    replica and bumps the shard's epoch — the fencing token every
    subsequent FlowMod to that shard carries."""

    def __init__(self, count: int = 2, index: int = 0) -> None:
        if not 0 <= index < max(1, count):
            raise ValueError(f"replica index {index} outside 0..{count - 1}")
        self.count = max(1, count)
        self.index = index
        self.assignment: dict[int, int] = {
            s: s for s in range(self.count)
        }
        self.epoch: dict[int, int] = {s: 0 for s in range(self.count)}

    def shard_of(self, dpid: int) -> int:
        return int(dpid) % self.count

    def owner_of(self, dpid: int) -> int:
        return self.assignment[self.shard_of(dpid)]

    def owns(self, dpid: int) -> bool:
        return self.owner_of(dpid) == self.index

    def shards_of(self, replica: int) -> list[int]:
        """The shards ``replica`` currently serves."""
        return sorted(
            s for s, owner in self.assignment.items() if owner == replica
        )

    def adopt(self, shard: int) -> int:
        """Take over ``shard`` (its previous owner's lease expired):
        reassign it here and bump its epoch. Returns the new epoch —
        the fencing token of the post-failover regime."""
        self.assignment[shard] = self.index
        self.epoch[shard] = self.epoch.get(shard, 0) + 1
        return self.epoch[shard]

    def cookie_token(self, dpid: int) -> int:
        """The cookie fencing this switch's current regime."""
        shard = self.shard_of(dpid)
        return cookie_token(shard, self.epoch.get(shard, 0))

    def to_dict(self) -> dict:
        """Status payload for heartbeats / the replica_status pull."""
        return {
            "count": self.count,
            "index": self.index,
            "assignment": dict(self.assignment),
            "epoch": dict(self.epoch),
        }
