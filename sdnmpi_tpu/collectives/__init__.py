from sdnmpi_tpu.collectives.patterns import (  # noqa: F401
    collective_pairs,
    alltoall_pairs,
    allreduce_ring_pairs,
    allreduce_recursive_doubling_pairs,
    bcast_binomial_pairs,
    allgather_ring_pairs,
    reduce_binomial_pairs,
    gather_pairs,
    scatter_pairs,
    barrier_dissemination_pairs,
)
