"""MPI collective communication patterns as rank-pair batches.

The reference understands collectives only as a type code logged from the
virtual MAC (reference: sdnmpi/router.py:176,182) — routing stays
one-pair-at-a-time. Here each collective expands into the full batch of
(src_rank, dst_rank) pairs its algorithm sends, so the oracle can score
and install every route of the collective at once (the north star:
"score all rank-pair paths of an MPI collective at once").

Patterns follow the textbook algorithms (binomial trees for rooted
collectives, rings and recursive doubling for all-to-all-style ones);
each function returns an ``[F, 2]`` int32 array of rank pairs, optionally
with a round index for phase-aware scheduling.
"""

from __future__ import annotations

import numpy as np

from sdnmpi_tpu.protocol.vmac import CollectiveType


def alltoall_pairs(n: int) -> np.ndarray:
    """Every ordered pair (i, j), i != j: the complete traffic matrix."""
    src, dst = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    mask = src != dst
    return np.stack([src[mask], dst[mask]], axis=1).astype(np.int32)


def bcast_binomial_pairs(n: int, root: int = 0, with_rounds: bool = False):
    """Binomial-tree broadcast: log2(n) rounds; in round k every rank that
    already holds the data forwards it 2^k ranks ahead (relative to root).
    """
    pairs, rounds = [], []
    k = 0
    while (1 << k) < n:
        step = 1 << k
        for rel in range(step):
            if rel + step < n:
                src = (root + rel) % n
                dst = (root + rel + step) % n
                pairs.append((src, dst))
                rounds.append(k)
        k += 1
    return _with_rounds(pairs, rounds, with_rounds)


def reduce_binomial_pairs(n: int, root: int = 0, with_rounds: bool = False):
    """Binomial-tree reduce: the broadcast tree with edges reversed."""
    pairs, rounds = bcast_binomial_pairs(n, root, with_rounds=True)
    pairs = pairs[:, ::-1].copy()
    max_round = rounds.max(initial=0)
    rounds = max_round - rounds
    order = np.argsort(rounds, kind="stable")
    pairs, rounds = pairs[order], rounds[order]
    return (pairs, rounds) if with_rounds else pairs


def allreduce_ring_pairs(n: int, with_rounds: bool = False):
    """Ring allreduce: 2(n-1) rounds of neighbor sends (reduce-scatter then
    allgather), each round the full ring (i -> i+1)."""
    pairs, rounds = [], []
    for r in range(2 * (n - 1)):
        for i in range(n):
            pairs.append((i, (i + 1) % n))
            rounds.append(r)
    return _with_rounds(pairs, rounds, with_rounds)


def allreduce_recursive_doubling_pairs(n: int, with_rounds: bool = False):
    """Recursive doubling: log2(n) rounds of pairwise exchange with the
    rank whose index differs in bit k. Requires power-of-two n."""
    if n & (n - 1):
        raise ValueError(f"recursive doubling needs power-of-two ranks, got {n}")
    pairs, rounds = [], []
    k = 0
    while (1 << k) < n:
        for i in range(n):
            pairs.append((i, i ^ (1 << k)))
            rounds.append(k)
        k += 1
    return _with_rounds(pairs, rounds, with_rounds)


def allgather_ring_pairs(n: int, with_rounds: bool = False):
    """Ring allgather: n-1 rounds of (i -> i+1)."""
    pairs, rounds = [], []
    for r in range(n - 1):
        for i in range(n):
            pairs.append((i, (i + 1) % n))
            rounds.append(r)
    return _with_rounds(pairs, rounds, with_rounds)


def gather_pairs(n: int, root: int = 0) -> np.ndarray:
    """Flat gather: every non-root rank sends to root."""
    return np.array(
        [(i, root) for i in range(n) if i != root], dtype=np.int32
    ).reshape(-1, 2)


def scatter_pairs(n: int, root: int = 0) -> np.ndarray:
    return np.array(
        [(root, i) for i in range(n) if i != root], dtype=np.int32
    ).reshape(-1, 2)


def barrier_dissemination_pairs(n: int, with_rounds: bool = False):
    """Dissemination barrier: ceil(log2(n)) rounds; round k sends to
    (i + 2^k) mod n."""
    pairs, rounds = [], []
    k = 0
    while (1 << k) < n:
        step = 1 << k
        for i in range(n):
            pairs.append((i, (i + step) % n))
            rounds.append(k)
        k += 1
    return _with_rounds(pairs, rounds, with_rounds)


def _with_rounds(pairs, rounds, with_rounds: bool):
    arr = np.array(pairs, dtype=np.int32).reshape(-1, 2)
    if with_rounds:
        return arr, np.array(rounds, dtype=np.int32)
    return arr


#: CollectiveType -> generator for the pairs the collective transmits
_GENERATORS = {
    CollectiveType.BCAST: bcast_binomial_pairs,
    CollectiveType.REDUCE: reduce_binomial_pairs,
    CollectiveType.ALLREDUCE: allreduce_ring_pairs,
    CollectiveType.GATHER: gather_pairs,
    CollectiveType.SCATTER: scatter_pairs,
    CollectiveType.ALLGATHER: allgather_ring_pairs,
    CollectiveType.REDUCE_SCATTER: allgather_ring_pairs,  # same ring pattern
    CollectiveType.ALLTOALL: lambda n: alltoall_pairs(n),
    CollectiveType.BARRIER: barrier_dissemination_pairs,
}


def collective_pairs(coll_type: int, n: int, **kwargs) -> np.ndarray:
    """Rank pairs for a collective identified by its vMAC type code."""
    gen = _GENERATORS.get(coll_type)
    if gen is None:
        raise ValueError(f"no pattern for collective type {coll_type}")
    return gen(n, **kwargs)
